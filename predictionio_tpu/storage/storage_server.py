"""Server-mode storage: one process serving all three repositories over HTTP.

The reference's production stores are *server-mode* — HBase regionservers
for events, an Elasticsearch cluster for metadata
(``data/src/main/scala/io/prediction/data/storage/hbase/StorageClient.scala``,
``elasticsearch/StorageClient.scala``): many PredictionIO processes (CLI,
event server, training, serving) share state through a storage service on
the network. This module is the TPU rebuild's equivalent service: it exposes
a local registry's event/metadata/model stores over a small HTTP API that
``storage/remote.py`` clients consume, so multiple hosts (e.g. every worker
of a multi-host TPU pod) can share one storage endpoint.

Wire surface (all JSON unless noted):

* ``POST /events/<app_id>``            insert one event → ``{"eventId"}``
* ``POST /events/<app_id>/batch``      bulk write ``[event, ...]``
* ``GET|DELETE /events/<app_id>/<id>`` point get / delete
* ``POST /events/<app_id>/find``       body = filter dict → **ndjson** stream
* ``POST /events/<app_id>/init|remove`` lifecycle
* ``POST /metadata/rpc``               ``{"method", "args"}`` → ``{"result"}``
  (whitelisted MetadataStore methods; dataclasses encoded by ``wire.py``)
* ``PUT|GET|DELETE /models/<id>``      raw model bytes
* ``GET /``                            ``{"status": "alive", ...}`` readiness
  (Event-Server parity, ``EventAPI.scala:168-175``) with uptime and the
  backing store classes
* ``GET /health``                      liveness probe (kept for existing
  probes; ``GET /`` is the richer twin)
* ``GET /status.json``                 machine-readable status: role
  (primary/replica), changefeed seq, replication lag on replicas
* ``GET /replicate/changes``           ``?since=<seq>&limit=N`` — batched
  changefeed records for replica tailing (``docs/storage.md#replication``)
* ``GET /replicate/checkpoint``        current seq + store fingerprint
  (the oplog generation id)
* ``POST /replicate/promote``          replica only: stop tailing, start
  accepting writes with a fresh changefeed

When a :class:`~predictionio_tpu.storage.changefeed.Changefeed` is
attached (``create_storage_server(oplog_dir=...)``, the ``pio
storageserver`` default), every mutating response carries the assigned
sequence number in ``X-PIO-Seq`` — the client's read-your-writes token.
Replica servers reject mutations with ``409`` + a primary hint, and gate
reads carrying ``X-PIO-Min-Seq`` on their applied seq (wait-or-reject;
see ``storage/replica.py``).

Requests may carry an ``X-PIO-Deadline-Ms`` header (remaining budget in
milliseconds, set by the ``storage/remote.py`` client when an ambient
request deadline is live): an already-expired request is answered with
``504`` before any store work runs.

Run it with ``pio storageserver`` or :func:`create_storage_server`.
"""

from __future__ import annotations

import contextlib
import datetime as _dt
import json
import logging
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..api.http import BackgroundHTTPServer, JsonHTTPHandler
from ..obs.trace import TRACE_HEADER, Tracer
from ..utils.resilience import DEADLINE_HEADER, Deadline
from .changefeed import MIN_SEQ_HEADER, SEQ_HEADER, WrongPartition
from .event import Event
from .events import EventFilter
from .metadata import MetadataStore
from .oplog import OpLogGap
from .wire import decode, encode

logger = logging.getLogger(__name__)

DEFAULT_PORT = 7079

#: MetadataStore methods callable over /metadata/rpc. Everything public and
#: data-plane-free; an explicit list so a future store method with side
#: effects outside storage cannot be reached remotely by accident.
METADATA_RPC_METHODS = frozenset(
    {
        "gen_next",
        "app_insert",
        "app_get",
        "app_get_by_name",
        "app_get_all",
        "app_update",
        "app_delete",
        "access_key_insert",
        "access_key_get",
        "access_key_get_by_app",
        "access_key_delete",
        "manifest_update",
        "manifest_get",
        "engine_instance_insert",
        "engine_instance_get",
        "engine_instance_get_all",
        "engine_instance_get_latest_completed",
        "engine_instance_update",
        "engine_instance_delete",
        "evaluation_instance_insert",
        "evaluation_instance_get",
        "evaluation_instance_get_completed",
        "evaluation_instance_update",
        "rollout_plan_upsert",
        "rollout_plan_get",
        "rollout_plan_get_all",
        "rollout_plan_get_active",
        "rollout_plan_get_latest",
    }
)

#: Pure-read subset of the RPC surface: safe on replicas, safe to retry
#: on stale keep-alive connections (``remote.py`` aliases this). The
#: mutating complement lives in ``changefeed.METADATA_MUTATING_METHODS``;
#: ``tests/test_replication.py`` pins that the two partition the whole.
METADATA_READ_METHODS = frozenset(
    {
        "app_get",
        "app_get_by_name",
        "app_get_all",
        "access_key_get",
        "access_key_get_by_app",
        "manifest_get",
        "engine_instance_get",
        "engine_instance_get_all",
        "engine_instance_get_latest_completed",
        "evaluation_instance_get",
        "evaluation_instance_get_completed",
        "rollout_plan_get",
        "rollout_plan_get_all",
        "rollout_plan_get_active",
        "rollout_plan_get_latest",
    }
)


def _parse_filter(obj: dict) -> EventFilter:
    kwargs = dict(obj)
    for key in ("start_time", "until_time"):
        if kwargs.get(key) is not None:
            kwargs[key] = _dt.datetime.fromisoformat(kwargs[key])
    return EventFilter(**kwargs)


class _StorageHandler(JsonHTTPHandler):
    server: "StorageServer"

    # -- observability ----------------------------------------------------
    @contextlib.contextmanager
    def _obs_scope(self, method: str, op: str):
        """Admission span (joins the caller's ``X-PIO-Trace``) + op
        latency histogram around one data-plane route. ``op`` is the
        coarse route family (events/metadata/models/replicate) — the
        bounded label; never an app or record id."""
        server = self.server
        started = server.metrics.clock()
        try:
            with server.tracer.server_span(
                f"{method} /{op}",
                header_value=self.headers.get(TRACE_HEADER),
                tags={"op": op},
            ):
                yield
        finally:
            server.metrics.histogram(
                "pio_storage_op_seconds",
                "Storage server op latency by route family",
                labelnames=("method", "op"),
            ).observe(
                server.metrics.clock() - started, method=method, op=op
            )

    # -- routing ----------------------------------------------------------
    def _route(self, method: str) -> None:
        self._headers_sent = False  # reset per request (keep-alive reuse)
        path = urlparse(self.path).path.rstrip("/")
        parts = [p for p in path.split("/") if p]
        try:
            # Deadline admission: a request whose budget is already gone
            # must not spend store work producing an answer nobody waits
            # for (the client gave up remaining_ms ago).
            deadline = Deadline.from_header(self.headers.get(DEADLINE_HEADER))
            if deadline is not None and deadline.expired:
                self.read_body()
                self.respond(504, {"message": "deadline exceeded"})
                return
            if not parts and method == "GET":
                self.respond(200, self.server.status_json())
            elif parts == ["health"]:
                self.respond(200, {"status": "alive"})
            elif parts == ["status.json"] and method == "GET":
                self.respond(200, self.server.status_json())
            elif parts == ["replication.json"] and method == "GET":
                # per-partition replication rows (docs/storage.md
                # #partitioning): a storage node reports its own slot;
                # the event server aggregates its client-side view of
                # all N — ``pio top`` renders both as the PARTS column
                self.respond(200, self.server.replication_json())
            elif method == "GET" and parts in (
                ["metrics"], ["traces.json"],
                ["health.json"], ["blackbox.json"],
            ):
                # docs/observability.md + docs/slo.md — without the
                # health route, `pio health` cannot read a storage
                # node's per-partition freshness objectives
                self.serve_obs("/" + parts[0])
            elif parts and parts[0] == "replicate":
                with self._obs_scope(method, "replicate"):
                    self._route_replicate(method, parts[1:])
            elif not self._gate_min_seq(deadline):
                pass  # replica behind the caller's seq token: 409 sent
            elif parts and parts[0] == "events":
                with self._obs_scope(method, "events"):
                    self._route_events(method, parts[1:])
            elif parts == ["metadata", "rpc"] and method == "POST":
                with self._obs_scope(method, "metadata"):
                    self._metadata_rpc()
            elif parts and parts[0] == "models" and len(parts) == 2:
                with self._obs_scope(method, "models"):
                    self._route_models(method, parts[1])
            else:
                self.read_body()
                self.respond(404, {"message": "Not found"})
        except WrongPartition as exc:
            # hash-contract violation: a write routed to a primary that
            # does not own its key. 409 + the owning index — loud and
            # actionable for a misconfigured client, never a silent fork
            # of the keyspace (write paths never stream, so headers are
            # still ours to send).
            self.respond(
                409,
                {
                    "message": str(exc),
                    "expectedPartition": exc.expected,
                    "partition": list(self.server.partition),
                },
            )
        except (BrokenPipeError, ConnectionResetError) as exc:
            # client dropped mid-stream (abandoned scan): normal operation
            logger.debug("client dropped during %s %s: %s", method, path, exc)
            self.close_connection = True
            return
        except Exception as exc:  # one bad request must not kill the server
            logger.exception("storage server error on %s %s", method, path)
            if getattr(self, "_headers_sent", False):
                # Mid-stream failure: a second response would corrupt the
                # chunked framing. Drop the connection so the client fails
                # loudly instead of parsing a truncated stream as complete.
                self.close_connection = True
                return
            try:
                self.respond(500, {"message": f"{type(exc).__name__}: {exc}"})
            except Exception:
                pass  # client hung up mid-response

    # -- replication plumbing --------------------------------------------
    def _gate_min_seq(self, deadline: Optional[Deadline]) -> bool:
        """Read-your-writes gate: a request carrying ``X-PIO-Min-Seq``
        proceeds only once this server has applied that seq (trivially
        true on a primary). Returns False after sending the 409."""
        raw = self.headers.get(MIN_SEQ_HEADER)
        if raw is None:
            return True
        try:
            min_seq = int(raw)
        except ValueError:
            return True  # garbled header degrades to an ungated read
        if self.server.wait_for_seq(min_seq, deadline):
            return True
        self.read_body()
        self.respond(
            409,
            {
                "message": "replica behind requested seq",
                "appliedSeq": self.server.applied_seq(),
                "minSeq": min_seq,
                "primary": self.server.primary_url,
            },
        )
        return False

    def _reject_writes(self) -> bool:
        """On a replica, answer a mutating request with 409 + the primary
        hint. Returns True when the request was consumed."""
        if self.server.accepts_writes:
            return False
        self.read_body()
        self.respond(
            409,
            {
                "message": "replica: writes must go to the primary",
                "primary": self.server.primary_url,
            },
        )
        return True

    @staticmethod
    def _seq_headers(seq) -> Optional[dict]:
        return {SEQ_HEADER: seq} if seq is not None else None

    def _route_replicate(self, method: str, rest: list) -> None:
        cf = self.server.changefeed
        if rest == ["changes"] and method == "GET":
            if cf is None:
                self.respond(404, {"message": "changefeed disabled"})
                return
            q = parse_qs(urlparse(self.path).query)
            since = int(q.get("since", ["0"])[0])
            limit = min(max(1, int(q.get("limit", ["500"])[0])), 1000)
            try:
                entries, last_seq = cf.oplog.read_since(since, limit)
            except OpLogGap as exc:
                self.respond(410, {"message": str(exc), **cf.oplog.checkpoint()})
                return
            body = {
                "changes": [{"seq": s, "op": o} for s, o in entries],
                "lastSeq": last_seq,
                "generation": cf.oplog.generation,
                "oldestSeq": cf.oplog.oldest_seq,
            }
            if cf.oplog.partition is not None:
                # tailers verify they follow the slot they were
                # configured for (storage/partition.check_partition)
                body["partition"] = list(cf.oplog.partition)
            self.respond(200, body)
        elif rest == ["checkpoint"] and method == "GET":
            ck = self.server.checkpoint_json()
            if ck is None:
                self.respond(404, {"message": "changefeed disabled"})
                return
            ck["stores"] = self.server.status_json()["stores"]
            self.respond(200, ck)
        elif rest == ["promote"] and method == "POST":
            self.read_body()
            result = self.server.promote()
            if result is None:
                self.respond(409, {"message": "not a replica"})
            else:
                self.respond(200, result)
        else:
            self.read_body()
            self.respond(404, {"message": "Not found"})

    def do_GET(self) -> None:  # noqa: N802
        self._route("GET")

    def do_POST(self) -> None:  # noqa: N802
        self._route("POST")

    def do_PUT(self) -> None:  # noqa: N802
        self._route("PUT")

    def do_DELETE(self) -> None:  # noqa: N802
        self._route("DELETE")

    # -- events -----------------------------------------------------------
    def _route_events(self, method: str, parts: list) -> None:
        store = self.server.events
        if not parts:
            self.read_body()
            self.respond(404, {"message": "Missing app id"})
            return
        app_id = int(parts[0])
        rest = parts[1:]
        cf = self.server.changefeed
        if method == "POST" and not rest:
            if self._reject_writes():
                return
            event = Event.from_json_dict(json.loads(self.read_body()))
            if cf is not None:
                event_id, seq = cf.insert_event(event, app_id)
                self.respond(
                    201, {"eventId": event_id}, headers=self._seq_headers(seq)
                )
            else:
                self.respond(201, {"eventId": store.insert(event, app_id)})
        elif method == "POST" and rest == ["batch"]:
            if self._reject_writes():
                return
            fresh = parse_qs(urlparse(self.path).query).get("fresh")
            fresh = bool(fresh and fresh[0] == "1")
            events = [
                Event.from_json_dict(o) for o in json.loads(self.read_body())
            ]
            seq = None
            if cf is not None:
                seq = cf.write_events(events, app_id, fresh)
            elif fresh:
                store.write_new(events, app_id)
            else:
                store.write(events, app_id)
            self.respond(
                200, {"count": len(events)}, headers=self._seq_headers(seq)
            )
        elif method == "POST" and rest == ["find"]:
            flt = _parse_filter(json.loads(self.read_body() or b"{}"))
            self._stream_events(store.find(app_id, flt))
        elif method == "POST" and rest == ["scan_columnar"]:
            flt = _parse_filter(json.loads(self.read_body() or b"{}"))
            self._scan_columnar(store, app_id, flt)
        elif method == "POST" and rest == ["init"]:
            if self._reject_writes():
                return
            self.read_body()
            if cf is not None:
                ok, seq = cf.init_app(app_id)
                self.respond(200, {"ok": ok}, headers=self._seq_headers(seq))
            else:
                self.respond(200, {"ok": store.init(app_id)})
        elif method == "POST" and rest == ["remove"]:
            if self._reject_writes():
                return
            self.read_body()
            if cf is not None:
                ok, seq = cf.remove_app(app_id)
                self.respond(200, {"ok": ok}, headers=self._seq_headers(seq))
            else:
                self.respond(200, {"ok": store.remove(app_id)})
        elif method == "GET" and len(rest) == 1:
            event = store.get(rest[0], app_id)
            if event is None:
                self.respond(404, {"message": "Not found"})
            else:
                self.respond(200, event.to_json_dict())
        elif method == "DELETE" and len(rest) == 1:
            if self._reject_writes():
                return
            if cf is not None:
                found, seq = cf.delete_event(rest[0], app_id)
                self.respond(
                    200, {"found": found}, headers=self._seq_headers(seq)
                )
            else:
                self.respond(200, {"found": store.delete(rest[0], app_id)})
        else:
            self.read_body()
            self.respond(404, {"message": "Not found"})

    def _stream_events(self, events) -> None:
        """ndjson chunked stream — the scan never materializes server-side,
        so an arbitrarily large app streams in bounded memory (the HBase
        scanner-caching analogue, ``HBPEvents.scala:85``)."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        self._headers_sent = True

        def chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")

        buf = bytearray()
        for event in events:
            buf += json.dumps(event.to_json_dict()).encode() + b"\n"
            if len(buf) >= 64 * 1024:
                chunk(bytes(buf))
                buf.clear()
        if buf:
            chunk(bytes(buf))
        self.wfile.write(b"0\r\n\r\n")

    def _scan_columnar(self, store, app_id: int, flt: EventFilter) -> None:
        """Columnar fast path. Delegates to the backing store's native
        columnar scan when it has one; otherwise derives the columns from
        ``find`` so every backend honors the contract."""
        if hasattr(store, "scan_columnar"):
            cols = dict(store.scan_columnar(app_id, flt))
            cols["event_time_ms"] = [int(v) for v in cols["event_time_ms"]]
        else:
            from .event import to_millis

            cols = {
                "event": [], "entity_type": [], "entity_id": [],
                "target_entity_type": [], "target_entity_id": [],
                "properties": [], "event_time_ms": [],
            }
            for e in store.find(app_id, flt):
                cols["event"].append(e.event)
                cols["entity_type"].append(e.entity_type)
                cols["entity_id"].append(e.entity_id)
                cols["target_entity_type"].append(e.target_entity_type)
                cols["target_entity_id"].append(e.target_entity_id)
                cols["properties"].append(e.properties.to_dict())
                cols["event_time_ms"].append(to_millis(e.event_time))
        self.respond(200, cols)

    # -- metadata ---------------------------------------------------------
    def _metadata_rpc(self) -> None:
        req = json.loads(self.read_body())
        method = req.get("method", "")
        if method not in METADATA_RPC_METHODS:
            self.respond(400, {"message": f"Unknown RPC method {method!r}"})
            return
        if method not in METADATA_READ_METHODS and not self.server.accepts_writes:
            self.respond(
                409,
                {
                    "message": "replica: writes must go to the primary",
                    "primary": self.server.primary_url,
                },
            )
            return
        args = [decode(a) for a in req.get("args", [])]
        cf = self.server.changefeed
        if cf is not None:
            result, seq = cf.metadata_rpc(method, args)
            self.respond(
                200, {"result": encode(result)}, headers=self._seq_headers(seq)
            )
        else:
            result = getattr(self.server.metadata, method)(*args)
            self.respond(200, {"result": encode(result)})

    # -- models -----------------------------------------------------------
    def _route_models(self, method: str, model_id: str) -> None:
        from .model_store import Model

        store = self.server.models
        cf = self.server.changefeed
        if method == "PUT":
            if self._reject_writes():
                return
            model = Model(id=model_id, models=self.read_body())
            if cf is not None:
                seq = cf.put_model(model)
                self.respond(200, {"ok": True}, headers=self._seq_headers(seq))
            else:
                store.insert(model)
                self.respond(200, {"ok": True})
        elif method == "GET":
            model = store.get(model_id)
            if model is None:
                self.respond(404, {"message": "Not found"})
            else:
                self.respond(200, model.models, content_type="application/octet-stream")
        elif method == "DELETE":
            if self._reject_writes():
                return
            if cf is not None:
                seq = cf.delete_model(model_id)
                self.respond(200, {"ok": True}, headers=self._seq_headers(seq))
            else:
                store.delete(model_id)
                self.respond(200, {"ok": True})
        else:
            self.read_body()
            self.respond(404, {"message": "Not found"})


class StorageServer(BackgroundHTTPServer):
    """HTTP front for one set of backing stores.

    With ``changefeed`` attached, the server is a replication *primary*:
    mutations are sequence-numbered and shipped via ``/replicate/*``.
    The replica twin lives in ``storage/replica.py``
    (:class:`StorageReplica` subclasses this and flips
    ``accepts_writes``)."""

    #: replicas flip this to False and reject mutations with 409
    accepts_writes = True
    #: the write endpoint to hint in replica 409s (None on a primary)
    primary_url: Optional[str] = None
    #: tracer service name ("storage-replica" on replicas)
    service_name = "storage-server"

    def __init__(
        self,
        host: str,
        port: int,
        events,
        metadata: MetadataStore,
        models,
        changefeed=None,
        partition: Optional[tuple] = None,
    ):
        super().__init__(
            (host, port), _StorageHandler, tracer=Tracer(self.service_name),
            health_kind="storage",
        )
        self.events = events
        self.metadata = metadata
        self.models = models
        self.changefeed = changefeed
        #: explicit ``(index, count)`` slot; the changefeed's own slot
        #: (from the oplog meta) wins when one is attached — see the
        #: ``partition`` property
        self._partition = (
            (int(partition[0]), int(partition[1]))
            if partition is not None
            else (0, 1)
        )
        self.start_time = _dt.datetime.now(tz=_dt.timezone.utc)
        # The changefeed seq is the append *counter* of the mutation log:
        # a scraper's rate() over it IS the append rate, and comparing it
        # across primary and replicas is the fleet's lag view. Pulled at
        # collect time so attaching a changefeed post-construction (the
        # loadgen chaos harness does) needs no re-wiring.
        self.metrics.gauge_callback(
            "pio_changefeed_seq",
            lambda: (
                self.changefeed.last_seq if self.changefeed is not None else 0
            ),
            "Last sequence number appended to the changefeed op log",
        )

    @property
    def partition(self) -> tuple:
        """This node's ``(index, count)`` keyspace slot. Derived from the
        attached changefeed when it carries one (the oplog meta is the
        durable identity — it survives restarts that lose CLI flags),
        else the construction-time value; ``(0, 1)`` = unpartitioned."""
        cf = self.changefeed
        if cf is not None and getattr(cf, "partition", (0, 1))[1] > 1:
            return cf.partition
        return self._partition

    def replication_json(self) -> dict:
        """``GET /replication.json`` — this node's per-partition rows
        (one row: itself). The event server's aggregated N-row twin and
        ``pio top``'s PARTS column read the same shape."""
        index, count = self.partition
        row = {
            "partition": index,
            "of": count,
            "up": True,
            "role": "primary" if self.accepts_writes else "replica",
            "seq": self.applied_seq(),
        }
        if self.changefeed is not None:
            row["generation"] = self.changefeed.oplog.generation
        return {"partitions": [row]}

    # -- replication hooks (overridden by StorageReplica) -----------------
    def applied_seq(self) -> int:
        """Highest seq this server has applied. A primary's stores are
        the authoritative state, so it equals the changefeed seq."""
        return self.changefeed.last_seq if self.changefeed is not None else 0

    def wait_for_seq(self, min_seq: int, deadline=None) -> bool:
        """Read-your-writes gate. A primary is always caught up with its
        own writes; replicas override with a bounded wait."""
        return True

    def promote(self) -> Optional[dict]:
        """Replica-only; a primary answers ``POST /replicate/promote``
        with 409 (signalled by None here)."""
        return None

    def checkpoint_json(self) -> Optional[dict]:
        """``GET /replicate/checkpoint`` body: seq + store fingerprint.
        Replicas override to answer from their applied state — the HA
        client's freshness probe must work on replicas, which have no
        changefeed until promoted. None → 404."""
        if self.changefeed is None:
            return None
        return self.changefeed.oplog.checkpoint()

    def status_json(self) -> dict:
        """``GET /`` readiness body — Event-Server ``{"status": "alive"}``
        parity plus enough identity for a fleet dashboard."""
        out = {
            "status": "alive",
            "role": "primary" if self.accepts_writes else "replica",
            "startTime": self.start_time.isoformat(timespec="milliseconds"),
            "stores": {
                "events": type(self.events).__name__,
                "metadata": type(self.metadata).__name__,
                "models": type(self.models).__name__,
            },
        }
        if self.changefeed is not None:
            out["seq"] = self.changefeed.last_seq
            out["generation"] = self.changefeed.oplog.generation
        if self.partition[1] > 1:
            out["partition"] = list(self.partition)
        return out


def create_storage_server(
    host: str = "127.0.0.1",
    port: int = DEFAULT_PORT,
    registry: Optional[object] = None,
    oplog_dir: Optional[str] = None,
    partition_index: int = 0,
    partition_count: int = 1,
    sync_every: Optional[int] = None,
) -> StorageServer:
    """Build a storage server fronting ``registry`` (default: the
    process-wide env-configured registry). ``oplog_dir`` attaches a
    changefeed rooted there, making the server a replication primary.
    ``partition_index``/``partition_count`` declare this primary's
    keyspace slot (docs/storage.md#partitioning) — stamped into the
    oplog meta and enforced on every event write. ``sync_every``
    overrides the oplog fsync cadence (1 = fsync before every ack:
    the strict power-loss-safe ack discipline)."""
    if registry is None:
        from .registry import get_registry

        registry = get_registry()
    if not (0 <= partition_index < max(1, partition_count)):
        raise ValueError(
            f"partition_index {partition_index} out of range for "
            f"partition_count {partition_count}"
        )
    events = registry.get_events()
    metadata = registry.get_metadata()
    models = registry.get_models()
    changefeed = None
    if oplog_dir is not None:
        from .changefeed import Changefeed
        from .oplog import DEFAULT_SYNC_EVERY, OpLog

        changefeed = Changefeed(
            OpLog(
                oplog_dir,
                sync_every=(
                    sync_every if sync_every is not None
                    else DEFAULT_SYNC_EVERY
                ),
                partition=(
                    (partition_index, partition_count)
                    if partition_count > 1
                    else None
                ),
            ),
            events, metadata, models,
        )
    return StorageServer(
        host, port, events, metadata, models, changefeed,
        partition=(partition_index, partition_count),
    )
