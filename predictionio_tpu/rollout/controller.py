"""RolloutController: promotion-gate evaluation over sliding windows.

The decision half of the rollout plane (``docs/rollouts.md``). The
query server feeds every served request into a per-variant
:class:`VariantWindow` (and every shadow comparison into a divergence
window); :meth:`RolloutController.evaluate` reduces those windows plus
the stage residence time to one of three verdicts:

- ``rollback`` — a gate is *violated* with enough evidence
  (``min_samples`` candidate observations). Fires immediately, at any
  stage; a failing candidate never waits out a hold timer.
- ``promote``  — every gate passes, the candidate has enough samples,
  and the stage's minimum hold time has elapsed.
- ``hold``     — not enough evidence yet, or the hold timer is still
  running. The default verdict: ambiguity never promotes and never
  rolls back.

Gates are deltas against the live baseline measured over the *same*
window — candidate error rate may exceed baseline's by at most
``max_error_rate_delta``, candidate p99 by at most
``max_p99_latency_ratio``×, and (shadow stage) mean prediction
divergence by at most ``max_divergence``. Comparing to the concurrent
baseline instead of absolute thresholds makes the policy robust to
fleet-wide weather (a slow storage day slows both variants equally).

The clock is injected, the windows are plain deques under one lock, and
nothing here touches storage or devices: the whole state machine's gate
logic runs in tier-1 tests with zero wall-clock sleeps.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Optional, Tuple

from ..storage.metadata import ROLLOUT_SHADOW
from .plan import GateConfig

__all__ = ["RolloutController", "VariantWindow"]

#: evaluate() verdicts
HOLD = "hold"
PROMOTE = "promote"
ROLLBACK = "rollback"


class VariantWindow:
    """Sliding window of (timestamp, latency, ok) samples for one
    variant. Bounded two ways: by age (``window_s``, pruned against the
    injected clock on every touch) and by count (``max_samples``, a
    memory cap — the gates need a recent representative sample, not
    every request at a million QPS).

    Gate evaluation runs on the serving hot path (once per request), so
    ``count``/``error_rate`` are O(1) off a running error counter; only
    ``p99`` pays a sort, and the caller only reaches it once both
    windows hold ``min_samples``."""

    def __init__(
        self,
        clock: Callable[[], float],
        window_s: float,
        max_samples: int = 4096,
    ):
        self._clock = clock
        self._window_s = window_s
        self._max_samples = max_samples
        self._samples: Deque[Tuple[float, float, bool]] = deque()
        self._errors = 0
        self._p99_cache: Optional[float] = None
        self._since_p99 = 0
        self._lock = threading.Lock()

    def record(self, latency_s: float, ok: bool) -> None:
        now = self._clock()
        with self._lock:
            if len(self._samples) >= self._max_samples:
                self._evict_oldest()
            self._samples.append((now, latency_s, ok))
            if not ok:
                self._errors += 1
            self._since_p99 += 1
            self._prune(now)

    def _evict_oldest(self) -> None:
        _t, _lat, ok = self._samples.popleft()
        if not ok:
            self._errors -= 1

    def _prune(self, now: float) -> None:
        cutoff = now - self._window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._evict_oldest()

    def count(self) -> int:
        with self._lock:
            self._prune(self._clock())
            return len(self._samples)

    def error_rate(self) -> float:
        with self._lock:
            self._prune(self._clock())
            if not self._samples:
                return 0.0
            return self._errors / len(self._samples)

    #: recompute the p99 sort at most once per this many new samples —
    #: evaluate() runs per request, and a per-request O(n log n) sort of
    #: a full window under the manager lock is hot-path poison; a p99
    #: that lags by <32 samples changes no gate decision
    _P99_REFRESH_EVERY = 32

    def p99(self) -> float:
        """p99 over the window: an exact sort, cached and refreshed
        every ``_P99_REFRESH_EVERY`` recorded samples."""
        with self._lock:
            self._prune(self._clock())
            if (
                self._p99_cache is not None
                and self._since_p99 < self._P99_REFRESH_EVERY
            ):
                return self._p99_cache
            lats = sorted(lat for _, lat, ok in self._samples if ok)
            if not lats:
                value = 0.0
            else:
                rank = max(0, min(len(lats) - 1, int(0.99 * len(lats))))
                value = lats[rank]
            self._p99_cache = value
            self._since_p99 = 0
            return value

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()
            self._errors = 0
            self._p99_cache = None
            self._since_p99 = 0


class RolloutController:
    """Gate evaluator for one rollout: owns the windows, the stage
    timer, and the promote/hold/rollback verdict."""

    def __init__(
        self,
        gates: GateConfig,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.gates = gates
        self.clock = clock
        self.baseline = VariantWindow(clock, gates.window_s)
        self.candidate = VariantWindow(clock, gates.window_s)
        self._divergence: Deque[Tuple[float, float]] = deque(maxlen=4096)
        self._div_lock = threading.Lock()
        self.stage_started = clock()
        #: optional served-score drift source for the ``max_score_psi``
        #: gate: a zero-arg callable returning the candidate's current
        #: PSI vs the quality monitor's pinned baseline, or None while
        #: there is not enough data (the gate abstains on None). The
        #: RolloutManager wires this to
        #: ``QualityMonitor.score_psi("candidate")`` — kept as an
        #: injected callable so the gate logic stays testable without a
        #: monitor (docs/observability.md#quality).
        self.quality_psi: Optional[Callable[[], Optional[float]]] = None

    # -- sample intake ----------------------------------------------------
    def record(self, variant_is_candidate: bool, latency_s: float, ok: bool) -> None:
        (self.candidate if variant_is_candidate else self.baseline).record(
            latency_s, ok
        )

    def record_divergence(self, value: float) -> None:
        now = self.clock()
        with self._div_lock:
            self._divergence.append((now, value))

    def mean_divergence(self) -> Optional[float]:
        cutoff = self.clock() - self.gates.window_s
        with self._div_lock:
            while self._divergence and self._divergence[0][0] < cutoff:
                self._divergence.popleft()
            values = [v for _, v in self._divergence]
        if not values:
            return None
        return sum(values) / len(values)

    def enter_stage(self) -> None:
        """Reset the residence timer on a stage transition. The metric
        windows carry over deliberately: a candidate that was erroring
        in shadow does not get a clean slate in canary."""
        self.stage_started = self.clock()

    def stage_elapsed_s(self) -> float:
        return max(0.0, self.clock() - self.stage_started)

    # -- verdict ----------------------------------------------------------
    def evaluate(self, stage: str) -> Tuple[str, str]:
        """One (verdict, reason) pair for the current windows. Pure with
        respect to the injected clock — calling it never mutates gate
        state beyond window pruning."""
        g = self.gates
        cand_n = self.candidate.count()
        base_n = self.baseline.count()

        # Violation checks first: enough candidate evidence + a tripped
        # gate rolls back NOW, hold timers notwithstanding.
        if cand_n >= g.min_samples:
            base_err = self.baseline.error_rate() if base_n else 0.0
            delta = self.candidate.error_rate() - base_err
            if delta > g.max_error_rate_delta:
                return ROLLBACK, (
                    f"error-rate delta {delta:.4f} exceeds "
                    f"{g.max_error_rate_delta:.4f} "
                    f"(candidate {self.candidate.error_rate():.4f} vs "
                    f"baseline {base_err:.4f} over {cand_n}/{base_n} samples)"
                )
            if base_n >= g.min_samples:
                base_p99 = self.baseline.p99()
                cand_p99 = self.candidate.p99()
                if base_p99 > 0 and cand_p99 > base_p99 * g.max_p99_latency_ratio:
                    return ROLLBACK, (
                        f"candidate p99 {cand_p99 * 1000:.2f}ms exceeds "
                        f"{g.max_p99_latency_ratio:.1f}x baseline p99 "
                        f"{base_p99 * 1000:.2f}ms"
                    )
            if stage == ROLLOUT_SHADOW:
                mean_div = self.mean_divergence()
                if mean_div is not None and mean_div > g.max_divergence:
                    return ROLLBACK, (
                        f"mean shadow divergence {mean_div:.4f} exceeds "
                        f"{g.max_divergence:.4f}"
                    )
            if g.max_score_psi > 0 and self.quality_psi is not None:
                # score-distribution drift (both stages: shadow answers
                # feed the candidate sketch too, so a skewed candidate
                # rolls back before it ever serves a user). Abstains on
                # None — "not enough data" must hold, never promote a
                # drift verdict either way.
                score_psi = self.quality_psi()
                if score_psi is not None and score_psi > g.max_score_psi:
                    return ROLLBACK, (
                        f"candidate score PSI {score_psi:.4f} exceeds "
                        f"{g.max_score_psi:.4f} vs the baseline score "
                        "distribution"
                    )

        if cand_n < g.min_samples:
            return HOLD, (
                f"waiting for candidate samples ({cand_n}/{g.min_samples})"
            )
        hold_s = g.shadow_hold_s if stage == ROLLOUT_SHADOW else g.canary_hold_s
        elapsed = self.stage_elapsed_s()
        if elapsed < hold_s:
            return HOLD, f"holding {stage} ({elapsed:.1f}/{hold_s:.1f}s)"
        return PROMOTE, (
            f"gates passed over {cand_n} candidate / {base_n} baseline "
            f"samples after {elapsed:.1f}s in {stage}"
        )
