"""Rollout policy primitives: gate config, sticky splits, divergence.

The pure half of the rollout plane (``docs/rollouts.md``): everything
here is a deterministic function of its inputs — no clocks, no storage,
no server state — so the routing and gate arithmetic is testable in
isolation and *provably* stable across process restarts and the HA
read-failover path (the sticky-split contract the ISSUE-5 satellites
pin).

- :class:`GateConfig` — the promotion-gate thresholds a
  :class:`~predictionio_tpu.rollout.controller.RolloutController`
  evaluates over sliding metric windows. Serialized into the durable
  ``RolloutPlan.gates`` dict so a restarted server resumes with the
  same policy it started under.
- :func:`variant_for_key` — the deterministic sticky traffic split:
  SHA-256 over ``salt|key`` into one of 10,000 buckets, candidate iff
  the bucket falls under ``percent``. No process state, no randomness:
  the same (salt, key, percent) triple answers identically everywhere,
  which is what makes a canary *sticky* — one user never flaps between
  models mid-session, even across a server crash or a metadata read
  served by a failed-over replica.
- :func:`prediction_divergence` — a [0, 1] structural distance between
  two encoded predictions, the shadow stage's "is the candidate even
  answering the same question" signal.

Like ``utils/resilience.py`` and ``obs/metrics.py``, this module is
stdlib-only and device-free.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Iterator, Tuple

from ..obs.quality import USER_KEY_FIELDS

__all__ = [
    "BASELINE",
    "CANDIDATE",
    "NUM_BUCKETS",
    "VARIANT_HEADER",
    "GateConfig",
    "bucket_for_key",
    "plan_epoch",
    "plan_to_json",
    "prediction_divergence",
    "sticky_key",
    "variant_for_key",
]

#: variant names — a closed two-value vocabulary, safe as a metric label
BASELINE = "baseline"
CANDIDATE = "candidate"

#: response header carrying the variant a query was SERVED by ("-" when
#: no rollout is involved). One home for the literal: the query server
#: stamps it, the router tier's fleet-consistency check reads it — a
#: divergent copy on either side would silently disarm the check
#: (docs/fleet.md).
VARIANT_HEADER = "X-PIO-Variant"

#: split resolution: percent maps to buckets out of 10,000 (0.01% steps)
NUM_BUCKETS = 10_000
_BUCKETS = NUM_BUCKETS

#: payload fields tried (in order) as the sticky entity key before
#: falling back to the whole canonicalized payload. The user-identity
#: prefix is the feedback join's field order too — shared from ONE home
#: (obs.quality, stdlib-only) or the served-list and feedback keys
#: silently diverge; item/id are sticky-only fallbacks for payloads
#: with no user field.
_ENTITY_KEY_FIELDS = USER_KEY_FIELDS + ("item", "id")


@dataclasses.dataclass(frozen=True)
class GateConfig:
    """Promotion-gate thresholds for one rollout.

    ``window_s``/``min_samples`` bound the sliding windows the gates
    read; the three gates themselves are *deltas against the baseline*,
    not absolutes — a candidate is judged by whether it made things
    worse, so the policy holds whether the fleet is fast or slow that
    day. ``*_hold_s`` is the minimum residence time per stage before
    auto-promotion (rollback is immediate — a failing gate never
    waits)."""

    window_s: float = 300.0
    min_samples: int = 50
    #: candidate error rate may exceed baseline's by at most this much
    max_error_rate_delta: float = 0.02
    #: candidate p99 may be at most this multiple of baseline p99
    max_p99_latency_ratio: float = 2.0
    #: mean shadow divergence ceiling (see prediction_divergence)
    max_divergence: float = 0.25
    shadow_hold_s: float = 60.0
    canary_hold_s: float = 120.0
    #: traffic share the candidate takes in the CANARY stage
    canary_percent: float = 10.0
    #: served-score distribution drift gate (docs/observability.md#quality):
    #: roll back when the candidate's score PSI vs the pinned baseline
    #: snapshot exceeds this. 0 disables (the default — PSI needs the
    #: quality monitor's min_psi_samples on both sides before it reports,
    #: and an engine whose predictions carry no scores never reports).
    #: Unlike the other gates this one is an absolute distribution
    #: distance, not a delta: PSI is already measured against the live
    #: baseline's own distribution. Conventional reading: <0.1 stable,
    #: >0.25 a real shift.
    max_score_psi: float = 0.0

    def to_dict(self) -> Dict[str, float]:
        return {
            f.name: float(getattr(self, f.name))
            for f in dataclasses.fields(self)
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "GateConfig":
        """Strict decode: an unknown key is a typo in an operator's gate
        override, and a typo that silently no-ops is a gate that never
        fires."""
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(data) - fields)
        if unknown:
            raise ValueError(
                f"unknown gate option(s) {unknown}; expected {sorted(fields)}"
            )
        kwargs = {k: float(v) for k, v in data.items()}
        if "min_samples" in kwargs:
            kwargs["min_samples"] = int(kwargs["min_samples"])
        return cls(**kwargs)


def plan_to_json(plan: Any) -> Dict[str, Any]:
    """The one camelCase wire shape of a ``RolloutPlan`` — shared by the
    query server's ``/rollout.json``/status pages and the dashboard's
    ``/rollouts.json`` so the two surfaces cannot drift."""
    return {
        "id": plan.id,
        "stage": plan.stage,
        "engineId": plan.engine_id,
        "engineVersion": plan.engine_version,
        "engineVariant": plan.engine_variant,
        "baselineInstanceId": plan.baseline_instance_id,
        "candidateInstanceId": plan.candidate_instance_id,
        "percent": plan.percent,
        "salt": plan.salt,
        "createdTime": str(plan.created_time),
        "updatedTime": str(plan.updated_time),
        "gates": dict(plan.gates),
        "history": list(plan.history),
    }


def plan_epoch(plan: Any) -> str:
    """The rollout plane's cache-invalidation epoch: a deterministic
    token over everything in a :class:`~predictionio_tpu.storage.metadata
    .RolloutPlan` that can change what a query is answered with — plan
    identity, stage, split (percent + salt), and both instance ids.
    ``updated_time`` rides along so ANY durable plan write moves the
    epoch (over-flushing is a wasted recompute; under-flushing is a
    stale answer).

    The router response cache (``fleet/cache.py``, docs/fleet.md#cache)
    stamps every entry with the epoch observed at fill time and drops
    any entry whose epoch no longer matches — a cached answer can never
    outlive the rollout stage that produced it, by construction. Pure
    function of the plan (``None`` — no active plan — is its own
    epoch), stdlib-only like everything in this module."""
    if plan is None:
        return "-"
    return "|".join(
        str(getattr(plan, field, ""))
        for field in (
            "id",
            "stage",
            "percent",
            "salt",
            "baseline_instance_id",
            "candidate_instance_id",
            "updated_time",
        )
    )


def sticky_key(payload: Any) -> str:
    """The identity a query is split on: the first conventional entity
    field present (``user``, ``entityId``, ...), else the whole payload
    canonicalized — every query still gets a *deterministic* assignment,
    just without cross-query stickiness for exotic shapes."""
    if isinstance(payload, dict):
        for field in _ENTITY_KEY_FIELDS:
            value = payload.get(field)
            if isinstance(value, (str, int, float, bool)):
                return f"{field}={value}"
    try:
        return json.dumps(payload, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return str(payload)


def bucket_for_key(salt: str, key: str) -> int:
    """The fleet's one hash: SHA-256 over ``salt|key`` into one of
    :data:`NUM_BUCKETS` buckets. Pure function of its two string inputs
    — no process state, no randomness — so every consumer (the canary
    split below, the router tier's replica affinity,
    ``docs/fleet.md``) computes the *same* bucket everywhere, with no
    coordination: any router replica and any query server agree on an
    assignment by construction. The golden-vector test in
    ``tests/test_rollout.py`` pins exact outputs — changing this
    function silently would flap every sticky assignment fleet-wide."""
    digest = hashlib.sha256(f"{salt}|{key}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") % _BUCKETS


def variant_for_key(salt: str, key: str, percent: float) -> str:
    """Deterministic sticky assignment: candidate iff the key's hash
    bucket (of 10,000) falls under ``percent``. The salt is minted once
    per plan, so consecutive rollouts sample *different* user subsets —
    the same 10% must not eat every canary's risk forever."""
    if percent <= 0:
        return BASELINE
    if percent >= 100:
        return CANDIDATE
    bucket = bucket_for_key(salt, key)
    return CANDIDATE if bucket < round(percent * (_BUCKETS / 100.0)) else BASELINE


def _leaves(obj: Any, path: Tuple = ()) -> Iterator[Tuple[Tuple, Any]]:
    """Flatten an encoded (JSON-shaped) prediction into (path, scalar)
    pairs; list positions are part of the path, so rank changes in a
    recommendation list surface as mismatches."""
    if isinstance(obj, dict):
        for key in sorted(obj):
            yield from _leaves(obj[key], path + (key,))
    elif isinstance(obj, (list, tuple)):
        for idx, item in enumerate(obj):
            yield from _leaves(item, path + (idx,))
    else:
        yield path, obj


def prediction_divergence(baseline: Any, candidate: Any) -> float:
    """Structural distance in [0, 1] between two *encoded* predictions.

    Per aligned leaf: numeric pairs contribute their relative distance
    ``|a-b| / (|a|+|b|)``; non-numeric pairs contribute 0 or 1 on
    equality; a leaf present on one side only contributes 1. The mean
    over the union of paths is the divergence. A heuristic, not a
    metric-space guarantee — its job is a stable 0 for "identical
    answer", a stable large value for "different model family", and
    monotone-ish behavior in between for the shadow gate to threshold.
    """
    la = dict(_leaves(baseline))
    lb = dict(_leaves(candidate))
    paths = set(la) | set(lb)
    if not paths:
        return 0.0
    total = 0.0
    for path in paths:
        if path not in la or path not in lb:
            total += 1.0
            continue
        va, vb = la[path], lb[path]
        num_a = isinstance(va, (int, float)) and not isinstance(va, bool)
        num_b = isinstance(vb, (int, float)) and not isinstance(vb, bool)
        if num_a and num_b:
            if va != vb:
                total += abs(va - vb) / (abs(va) + abs(vb))
        elif va != vb:
            total += 1.0
    return total / len(paths)
