"""RolloutManager: the query server's deployment-lifecycle state machine.

One manager per :class:`~predictionio_tpu.workflow.serving.QueryServer`
drives a candidate ``EngineInstance`` from trained to fully live
(``docs/rollouts.md``):

- **SHADOW** — the candidate is resident alongside the baseline; every
  served query is asynchronously duplicated to it on a bounded pool
  (results discarded, latency/error/prediction-divergence recorded per
  variant). Clients only ever see baseline answers.
- **CANARY** — a deterministic sticky share of traffic (hashed entity
  key, ``RolloutPlan.salt`` + ``percent``) is *served* by the
  candidate; a candidate failure falls back to the baseline inside the
  same request, so a sick canary costs latency, never a client error.
- **LIVE** — the candidate becomes ``server.deployment``; the retired
  baseline's model references are dropped so its device buffers are
  reclaimable.
- **ROLLED_BACK / ABORTED** — the candidate is retired, the baseline
  keeps 100% of traffic, and the terminal state (with the gate verdict
  as ``reason``) is durably recorded.

Transitions are decided by the
:class:`~predictionio_tpu.rollout.controller.RolloutController` after
every recorded sample and persisted through the metadata store — which
means they replicate through the PR-3 changefeed like any other
metadata mutation, and a server restarted mid-rollout resumes the same
plan (same salt → same sticky split) from
``rollout_plan_get_active``. A metadata outage during an automatic
transition never blocks serving: the in-memory state machine advances
and the write is retried on subsequent observations until it lands.
"""

from __future__ import annotations

import dataclasses
import logging
import secrets
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Deque, Optional

from ..storage import utcnow
from ..storage.event import to_millis
from ..obs.flight import record as flight_record
from ..storage.metadata import (
    ROLLOUT_ABORTED,
    ROLLOUT_CANARY,
    ROLLOUT_LIVE,
    ROLLOUT_ROLLED_BACK,
    ROLLOUT_SHADOW,
    RolloutPlan,
)
from .controller import PROMOTE, ROLLBACK, RolloutController
from .plan import (
    BASELINE,
    CANDIDATE,
    GateConfig,
    plan_to_json,
    prediction_divergence,
    sticky_key,
    variant_for_key,
)

logger = logging.getLogger(__name__)

__all__ = ["RolloutError", "RolloutManager"]

#: pio_rollout_stage gauge vocabulary (docs/rollouts.md)
_STAGE_CODES = {
    None: 0,
    ROLLOUT_SHADOW: 1,
    ROLLOUT_CANARY: 2,
    ROLLOUT_LIVE: 3,
    ROLLOUT_ROLLED_BACK: 4,
    ROLLOUT_ABORTED: 5,
}

#: divergence lives in [0, 1]: fixed linear-ish log buckets
_DIVERGENCE_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)

#: shadow duplicates in flight before new ones are dropped (counted as
#: kind="shadow_dropped") — shadow evaluation is sampling, not a queue
#: that may grow without bound when the candidate is slow
_SHADOW_PENDING_CAP = 32


class RolloutError(ValueError):
    """Operator-visible lifecycle misuse (no active plan, plan already
    active, unknown candidate, ...) → HTTP 409 on the rollout routes."""


class RolloutManager:
    """Owns one query server's rollout state: the durable plan, the
    resident candidate deployment, the gate controller, and the shadow
    duplication pool."""

    def __init__(self, server):
        self.server = server
        self.clock = server.clock
        self._lock = threading.RLock()
        self.plan: Optional[RolloutPlan] = None
        self.candidate_dep = None
        self.controller: Optional[RolloutController] = None
        #: set when a transition's metadata write failed; retried on the
        #: next observation until it lands (serving never blocks on it)
        self._persist_pending = False
        self._shadow_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="shadow"
        )
        self._shadow_pending = 0
        self._shadow_futures: Deque = deque(maxlen=256)
        #: max_score_psi gate cache: score_psi() merges full sketch
        #: copies, too heavy for every observe() — recomputed every
        #: _PSI_RECHECK_EVERY evaluates (count-based, not TTL: injected
        #: test clocks only advance when driven)
        self._psi_cached: Optional[float] = None
        self._psi_countdown = 0

        metrics = server.metrics
        self._hist = metrics.histogram(
            "pio_rollout_request_seconds",
            "Per-variant serving latency while a rollout is active",
            labelnames=("variant",),
        )
        self._events = metrics.counter(
            "pio_rollout_events_total",
            "Rollout serving outcomes by variant",
            labelnames=("variant", "kind"),
        )
        self._div_hist = metrics.histogram(
            "pio_rollout_divergence",
            "Shadow candidate-vs-baseline prediction divergence",
            buckets=_DIVERGENCE_BUCKETS,
        )
        self._transitions = metrics.counter(
            "pio_rollout_transitions_total",
            "Rollout plan state transitions",
            labelnames=("to",),
        )
        metrics.gauge_callback(
            "pio_rollout_stage",
            self._stage_code,
            "Rollout stage (0 none, 1 shadow, 2 canary, 3 live, "
            "4 rolled-back, 5 aborted)",
        )
        metrics.gauge_callback(
            "pio_rollout_percent",
            self._live_percent,
            "Traffic share the candidate currently serves",
        )

    # -- introspection ----------------------------------------------------
    # Scrape-thread gauge callbacks: these run on the /metrics handler
    # thread, so they take the manager lock like every other cross-thread
    # reader (conc-unguarded-attr). The critical section is two attribute
    # reads — a scrape can never convoy behind it.
    def _stage_code(self) -> int:
        with self._lock:
            plan = self.plan
            return _STAGE_CODES.get(plan.stage if plan else None, 0)

    def _live_percent(self) -> float:
        with self._lock:
            plan = self.plan
            if plan is None:
                return 0.0
            if plan.stage == ROLLOUT_CANARY:
                return float(plan.percent)
            return 100.0 if plan.stage == ROLLOUT_LIVE else 0.0

    @property
    def active(self) -> bool:
        plan = self.plan
        return plan is not None and plan.stage in (
            ROLLOUT_SHADOW,
            ROLLOUT_CANARY,
        )

    @property
    def stage(self) -> Optional[str]:
        plan = self.plan
        return plan.stage if plan else None

    def _md(self):
        return self.server.registry.get_metadata()

    # -- lifecycle --------------------------------------------------------
    def start(
        self,
        candidate_instance_id: Optional[str] = None,
        percent: Optional[float] = None,
        gates: Optional[dict] = None,
        reason: str = "rollout started",
    ) -> dict:
        """Open a new plan in SHADOW: load the candidate resident next
        to the baseline and persist the plan durably before the first
        duplicated query. ``reason`` lands in the plan history — the
        audit line distinguishing an operator start from the continuous
        controller's auto-submit (docs/continuous.md)."""
        from ..workflow.serving import prepare_deployment

        with self._lock:
            if self.active:
                raise RolloutError(
                    f"rollout {self.plan.id} is already active "
                    f"(stage {self.plan.stage}); promote or abort it first"
                )
            baseline = self.server.deployment.instance
        md = self._md()
        if candidate_instance_id:
            inst = md.engine_instance_get(candidate_instance_id)
            if inst is None:
                raise RolloutError(
                    f"engine instance {candidate_instance_id!r} not found"
                )
        else:
            # positional args: this call must survive the metadata RPC
            # wire, which ships {method, args} with no kwargs channel
            # (storage/remote.py _RemoteRPC)
            inst = md.engine_instance_get_latest_completed(
                baseline.engine_id,
                baseline.engine_version,
                baseline.engine_variant,
            )
            if inst is None or inst.id == baseline.id:
                raise RolloutError(
                    "no completed candidate newer than the deployed "
                    f"baseline {baseline.id}; train first or pass an "
                    "explicit instanceId"
                )
        gate_cfg = GateConfig.from_dict(gates or {})
        if percent is not None:
            gate_cfg = dataclasses.replace(
                gate_cfg, canary_percent=float(percent)
            )
        p = gate_cfg.canary_percent
        if not (0.0 < p <= 100.0):  # NaN fails both comparisons too
            raise RolloutError(
                f"canary percent must be in (0, 100], got {p!r} — a NaN or "
                "out-of-range split would 500 every canary query"
            )
        cfg = dataclasses.replace(
            self.server.config, engine_instance_id=inst.id
        )
        # Model load OUTSIDE the lock: status()/observe() share it, and
        # a minutes-long HBM upload must not hang every health probe.
        candidate_dep = prepare_deployment(
            self.server.engine, self.server.registry, cfg, self.server.ctx
        )
        with self._lock:
            if self.active:  # lost a race with a concurrent start
                raise RolloutError(
                    f"rollout {self.plan.id} is already active "
                    f"(stage {self.plan.stage}); promote or abort it first"
                )
            baseline = self.server.deployment.instance
            now = utcnow()
            plan = RolloutPlan(
                id="",
                stage=ROLLOUT_SHADOW,
                engine_id=baseline.engine_id,
                engine_version=baseline.engine_version,
                engine_variant=baseline.engine_variant,
                baseline_instance_id=baseline.id,
                candidate_instance_id=inst.id,
                percent=gate_cfg.canary_percent,
                salt=secrets.token_hex(8),
                created_time=now,
                updated_time=now,
                gates=gate_cfg.to_dict(),
                history=[self._history_entry(ROLLOUT_SHADOW, reason)],
            )
            pid = md.rollout_plan_upsert(plan)
            self.plan = dataclasses.replace(plan, id=pid)
            self.candidate_dep = candidate_dep
            self.controller = RolloutController(gate_cfg, clock=self.clock)
            self.controller.quality_psi = self._candidate_score_psi
            # a fresh rollout must judge THIS candidate's distribution:
            # drop any previous (possibly rolled-back-for-drift)
            # candidate's scores still inside the rolling window
            quality = getattr(self.server, "quality", None)
            if quality is not None:
                quality.reset_variant(CANDIDATE)
            self._psi_cached = None  # never judge THIS candidate by the
            self._psi_countdown = 0  # last one's cached drift
            self._persist_pending = False
            self._transitions.inc(1, to=ROLLOUT_SHADOW)
            flight_record(
                "rollout", "rollout.stage", plan=pid, to=ROLLOUT_SHADOW,
                candidate=inst.id,
            )
            logger.info(
                "rollout %s: candidate %s shadowing baseline %s",
                pid, inst.id, baseline.id,
            )
            return self.status()

    def resume(self) -> None:
        """Crash-consistent restart: re-resolve the active plan from
        metadata and rebuild the exact same routing function (same salt,
        same percent → same sticky split). Called from QueryServer
        construction; a missing/broken plan degrades to plain baseline
        serving, never a failed boot."""
        from ..workflow.serving import prepare_deployment

        with self._lock:
            deployed = self.server.deployment.instance
            md = self._md()
            plan = md.rollout_plan_get_active(
                deployed.engine_id,
                deployed.engine_version,
                deployed.engine_variant,
            )
            if plan is None:
                self._quarantine_rolled_back(md, deployed)
                return
            candidate_dep = None
            if deployed.id == plan.candidate_instance_id:
                # The candidate is the *latest completed* instance, so a
                # restarted server loaded it as its default deployment.
                # Mid-rollout that is wrong side of the split: reload the
                # plan's baseline and keep the candidate as candidate. An
                # unloadable baseline closes the plan loudly — leaving it
                # ACTIVE while the candidate serves 100% unwatched would
                # be the worst of both worlds.
                try:
                    cfg = dataclasses.replace(
                        self.server.config,
                        engine_instance_id=plan.baseline_instance_id,
                    )
                    baseline_dep = prepare_deployment(
                        self.server.engine, self.server.registry, cfg,
                        self.server.ctx,
                    )
                except Exception as exc:
                    self._persist_terminal(
                        plan,
                        ROLLOUT_ABORTED,
                        f"baseline unloadable on resume: {exc}; the "
                        "candidate remains deployed",
                    )
                    return
                with self.server._deploy_lock:
                    # the displaced deployment IS the candidate, already
                    # loaded — reuse it instead of paying a second model
                    # load (and doubling peak memory) on every
                    # mid-rollout restart
                    candidate_dep = self.server.deployment
                    self.server.deployment = baseline_dep
                self.server._export_train_phases()
            elif deployed.id != plan.baseline_instance_id:
                # A third instance got deployed out-of-band: the plan no
                # longer describes this server's traffic — finish it.
                self._persist_terminal(
                    plan,
                    ROLLOUT_ABORTED,
                    f"superseded by deployed instance {deployed.id}",
                )
                return
            if candidate_dep is None:
                try:
                    cfg = dataclasses.replace(
                        self.server.config,
                        engine_instance_id=plan.candidate_instance_id,
                    )
                    candidate_dep = prepare_deployment(
                        self.server.engine, self.server.registry, cfg,
                        self.server.ctx,
                    )
                except Exception as exc:
                    self._persist_terminal(
                        plan,
                        ROLLOUT_ABORTED,
                        f"candidate unloadable on resume: {exc}",
                    )
                    return
            gate_cfg = (
                GateConfig.from_dict(plan.gates) if plan.gates else GateConfig()
            )
            self.plan = plan
            self.candidate_dep = candidate_dep
            self.controller = RolloutController(gate_cfg, clock=self.clock)
            self.controller.quality_psi = self._candidate_score_psi
            logger.info(
                "rollout %s resumed at stage %s (candidate %s)",
                plan.id, plan.stage, plan.candidate_instance_id,
            )

    def _quarantine_rolled_back(self, md, deployed) -> None:
        """No active plan, but the instance this server just loaded (the
        *latest completed* one) may be the candidate a finished plan
        rolled back — redeploying it by default would undo the rollback
        on the next restart. Swap back to that plan's baseline; an
        explicit ``--engine-instance-id`` deploy still wins (operators
        can override quarantine deliberately)."""
        from ..workflow.serving import prepare_deployment

        if self.server.config.engine_instance_id:
            return  # explicitly pinned: respect the operator
        latest = md.rollout_plan_get_latest(
            deployed.engine_id, deployed.engine_version, deployed.engine_variant
        )
        if (
            latest is None
            or latest.stage not in (ROLLOUT_ROLLED_BACK, ROLLOUT_ABORTED)
            or deployed.id != latest.candidate_instance_id
        ):
            return
        try:
            cfg = dataclasses.replace(
                self.server.config,
                engine_instance_id=latest.baseline_instance_id,
            )
            baseline_dep = prepare_deployment(
                self.server.engine, self.server.registry, cfg, self.server.ctx
            )
        except Exception:
            # The quarantine could not be enforced — the rolled-back
            # candidate stays deployed. Surface the terminal plan so the
            # status page shows the situation instead of "no rollout".
            self.plan = latest
            logger.exception(
                "rollout %s: quarantine failed — baseline %s unloadable; "
                "the %s candidate %s remains deployed",
                latest.id, latest.baseline_instance_id, latest.stage,
                latest.candidate_instance_id,
            )
            return
        with self.server._deploy_lock:
            self.server.deployment = baseline_dep
        self.server._export_train_phases()
        self.plan = latest  # terminal plan surfaces in status pages
        logger.warning(
            "rollout %s: candidate %s is quarantined (%s); serving its "
            "baseline %s instead of the latest completed instance",
            latest.id, latest.candidate_instance_id, latest.stage,
            latest.baseline_instance_id,
        )

    def promote(self, reason: str = "manual promote") -> dict:
        """Operator override: advance one stage regardless of gates."""
        with self._lock:
            if not self.active:
                raise RolloutError("no active rollout to promote")
            self._advance_stage(reason)
            return self.status()

    def abort(self, reason: str = "manual abort") -> dict:
        """Operator override: retire the candidate, baseline takes 100%."""
        with self._lock:
            if not self.active:
                raise RolloutError("no active rollout to abort")
            self._retire_candidate(ROLLOUT_ABORTED, reason)
            return self.status()

    def close(self) -> None:
        self._shadow_pool.shutdown(wait=False)

    # -- request-path hooks (QueryServer.handle_query) --------------------
    def variant_for(self, payload: Any) -> str:
        """Sticky variant assignment for one query. Only the CANARY
        stage routes real traffic to the candidate."""
        plan = self.plan
        if plan is None or plan.stage != ROLLOUT_CANARY:
            return BASELINE
        if self.candidate_dep is None:
            return BASELINE
        return variant_for_key(plan.salt, sticky_key(payload), plan.percent)

    def candidate_deployment(self):
        return self.candidate_dep

    _PSI_RECHECK_EVERY = 16

    def _candidate_score_psi(self):
        """The ``max_score_psi`` gate's drift source: the candidate's
        served-score PSI off the server's quality monitor, None while
        there is not enough data (docs/observability.md#quality). Pure
        read — safe from evaluate() under the manager lock because the
        monitor takes only its own lock and never blocks. The value is
        recomputed every ``_PSI_RECHECK_EVERY`` evaluates: score_psi()
        merges full sketch copies, and drift moves on window
        timescales, not per request."""
        quality = getattr(self.server, "quality", None)
        if quality is None:
            return None
        self._psi_countdown -= 1
        if self._psi_countdown < 0:
            self._psi_cached = quality.score_psi(CANDIDATE)
            self._psi_countdown = self._PSI_RECHECK_EVERY - 1
        return self._psi_cached

    def observe(self, variant: str, latency_s: float, ok: bool) -> None:
        """Record one served request and re-evaluate the gates."""
        with self._lock:
            if not self.active or self.controller is None:
                return
            self.controller.record(variant == CANDIDATE, latency_s, ok)
            self._hist.observe(latency_s, variant=variant)
            self._events.inc(1, variant=variant, kind="ok" if ok else "error")
            self._maybe_advance()

    def retry_pending_persist(self) -> None:
        """Land a transition whose metadata write failed. Called once
        per served request (lock-free fast path when nothing is
        pending), because a *terminal* transition has no subsequent
        observe() to ride — without this, a rollback decided during a
        metadata outage would never become durable and a restarted
        server would resume the rolled-back plan."""
        if not self._persist_pending:
            return
        with self._lock:
            if self._persist_pending and self.plan is not None:
                self._try_persist(self.plan)

    def submit_shadow(self, payload: Any, baseline_result: Any):
        """Duplicate one query to the resident candidate (SHADOW stage):
        async on the bounded pool, result discarded, outcome recorded.
        Returns the Future (tests drain it) or None when dropped."""
        with self._lock:
            if (
                not self.active
                or self.plan.stage != ROLLOUT_SHADOW
                or self.candidate_dep is None
            ):
                return None
            if self._shadow_pending >= _SHADOW_PENDING_CAP:
                self._events.inc(1, variant=CANDIDATE, kind="shadow_dropped")
                return None
            self._shadow_pending += 1
            dep = self.candidate_dep
            plan_id = self.plan.id
        try:
            future = self._shadow_pool.submit(
                self._run_shadow, dep, payload, baseline_result, plan_id
            )
        except RuntimeError:  # pool shut down mid-stop
            with self._lock:
                self._shadow_pending -= 1
            return None
        with self._lock:
            self._shadow_futures.append(future)
        return future

    def drain_shadow(self, timeout_s: float = 30.0) -> None:
        """Wait for every outstanding shadow duplicate (deterministic
        tests and the loadgen chaos scenario; never called on the
        request path). The deque is popped under the manager lock —
        concurrent drains (or a drain racing submit_shadow) must never
        pop the same future twice or IndexError on an emptied deque —
        while the blocking result() wait happens outside it."""
        while True:
            with self._lock:
                if not self._shadow_futures:
                    # a deterministic drain exists so the NEXT gate read
                    # sees every drained score — drop the cached PSI or
                    # the post-drain evaluate can return a stale None
                    # for up to _PSI_RECHECK_EVERY more requests
                    self._psi_countdown = 0
                    return
                future = self._shadow_futures.popleft()
            future.result(timeout=timeout_s)

    def _run_shadow(self, dep, payload, baseline_result, plan_id) -> None:
        t0 = self.clock()
        divergence: Optional[float] = None
        ok = False
        try:
            from ..workflow.serving import encode_result

            _query, prediction = self.server._serve_one(
                dep, payload, None, CANDIDATE
            )
            encoded = encode_result(prediction)
            divergence = prediction_divergence(baseline_result, encoded)
            # the candidate's answers feed its score sketch even though
            # no client saw them: the max_score_psi gate can catch a
            # skewed candidate while it is still shadow-only
            # (docs/observability.md#quality). Only while OUR plan is
            # still the active one: a stale task from a rolled-back
            # rollout must not re-contaminate the window start() reset
            # for the next candidate — checked and recorded under the
            # ONE manager lock, or a rollback + next start() could slip
            # between an unlocked check and the record. (Safe to hold:
            # manager→monitor is the established ordering, and a
            # CANDIDATE record never writes a snapshot, so no I/O.)
            quality = getattr(self.server, "quality", None)
            if quality is not None:
                from ..obs.quality import scores_from_result

                scores = scores_from_result(encoded)[1]
                with self._lock:
                    if self.active and self.plan.id == plan_id:
                        quality.record_scores(CANDIDATE, scores)
            ok = True
        except Exception:
            logger.debug("shadow candidate query failed", exc_info=True)
        finally:
            elapsed = max(0.0, self.clock() - t0)
            with self._lock:
                self._shadow_pending -= 1
                if self.active and self.plan.stage == ROLLOUT_SHADOW:
                    self.controller.record(True, elapsed, ok)
                    self._hist.observe(elapsed, variant=CANDIDATE)
                    self._events.inc(
                        1,
                        variant=CANDIDATE,
                        kind="shadow_ok" if ok else "shadow_error",
                    )
                    if divergence is not None:
                        self.controller.record_divergence(divergence)
                        self._div_hist.observe(divergence)
                    self._maybe_advance()

    # -- state machine ----------------------------------------------------
    def _maybe_advance(self) -> None:
        """Gate check after each sample (lock held)."""
        if self._persist_pending:
            self._try_persist(self.plan)
        if not self.active or self.controller is None:
            return
        verdict, reason = self.controller.evaluate(self.plan.stage)
        if verdict == PROMOTE:
            self._advance_stage(reason)
        elif verdict == ROLLBACK:
            self._retire_candidate(ROLLOUT_ROLLED_BACK, reason)

    def _advance_stage(self, reason: str) -> None:
        """SHADOW → CANARY → LIVE (lock held)."""
        if self.plan.stage == ROLLOUT_SHADOW:
            self._set_stage(ROLLOUT_CANARY, reason)
            self.controller.enter_stage()
            logger.info(
                "rollout %s: candidate %s takes %.1f%% of traffic (%s)",
                self.plan.id, self.plan.candidate_instance_id,
                self.plan.percent, reason,
            )
            return
        # CANARY → LIVE: the candidate becomes THE deployment; the
        # retired baseline's last reference goes with the swap, so its
        # model buffers are reclaimable (in-flight queries finish on the
        # deployment they were routed to — they hold their own ref).
        candidate_dep = self.candidate_dep
        self.server._adopt_deployment(candidate_dep)
        self.candidate_dep = None
        self.controller = None
        self._set_stage(ROLLOUT_LIVE, reason)
        logger.info(
            "rollout %s: candidate %s is live, baseline %s retired (%s)",
            self.plan.id, self.plan.candidate_instance_id,
            self.plan.baseline_instance_id, reason,
        )

    def _retire_candidate(self, stage: str, reason: str) -> None:
        """Rollback/abort (lock held): drop the candidate, keep serving
        the resident baseline — the transition is a reference swap away
        from 100% baseline, never a client-visible event."""
        self.candidate_dep = None
        self.controller = None
        self._set_stage(stage, reason)
        logger.warning(
            "rollout %s: candidate %s retired -> %s (%s)",
            self.plan.id, self.plan.candidate_instance_id, stage, reason,
        )

    @staticmethod
    def _history_entry(stage: str, reason: str) -> dict:
        return {"stage": stage, "atMs": to_millis(utcnow()), "reason": reason}

    def _set_stage(self, stage: str, reason: str) -> None:
        self.plan = dataclasses.replace(
            self.plan,
            stage=stage,
            updated_time=utcnow(),
            history=list(self.plan.history)
            + [self._history_entry(stage, reason)],
        )
        self._transitions.inc(1, to=stage)
        # stage changes are the rollout plane's state transitions — the
        # flight recorder's core vocabulary (docs/slo.md)
        flight_record(
            "rollout", "rollout.stage", plan=self.plan.id, to=stage,
            reason=reason,
        )
        self._try_persist(self.plan)

    def _try_persist(self, plan: RolloutPlan) -> None:
        """Durably record ``plan``; a storage outage defers (retried on
        every subsequent observation) instead of failing the request
        that happened to trigger the transition."""
        try:
            self._md().rollout_plan_upsert(plan)
            self._persist_pending = False
        except Exception as exc:
            self._persist_pending = True
            logger.warning(
                "rollout %s: could not persist stage %s (%s); will retry",
                plan.id, plan.stage, exc,
            )

    def _persist_terminal(self, plan: RolloutPlan, stage: str, reason: str) -> None:
        """Finish a plan this manager is NOT adopting (resume-time
        supersede/abort paths)."""
        finished = dataclasses.replace(
            plan,
            stage=stage,
            updated_time=utcnow(),
            history=list(plan.history) + [self._history_entry(stage, reason)],
        )
        self.plan = finished
        self._transitions.inc(1, to=stage)
        flight_record(
            "rollout", "rollout.stage", plan=plan.id, to=stage,
            reason=reason,
        )
        self._try_persist(finished)
        logger.warning("rollout %s: %s (%s)", plan.id, stage, reason)

    # -- status -----------------------------------------------------------
    def status(self) -> dict:
        """The ``GET /rollout.json`` / ``pio rollout status`` body."""
        with self._lock:
            plan = self.plan
            out: dict = {"active": self.active}
            if plan is None:
                return out
            out["plan"] = plan_to_json(plan)
            if self.active and self.controller is not None:
                verdict, reason = self.controller.evaluate(plan.stage)
                mean_div = self.controller.mean_divergence()
                out["windows"] = {
                    "baseline": {
                        "samples": self.controller.baseline.count(),
                        "errorRate": round(
                            self.controller.baseline.error_rate(), 6
                        ),
                        "p99Ms": round(
                            self.controller.baseline.p99() * 1000, 3
                        ),
                    },
                    "candidate": {
                        "samples": self.controller.candidate.count(),
                        "errorRate": round(
                            self.controller.candidate.error_rate(), 6
                        ),
                        "p99Ms": round(
                            self.controller.candidate.p99() * 1000, 3
                        ),
                    },
                }
                if mean_div is not None:
                    out["windows"]["meanDivergence"] = round(mean_div, 6)
                out["decision"] = {"verdict": verdict, "reason": reason}
            return out
