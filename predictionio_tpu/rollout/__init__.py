"""Rollout plane: shadow serving, canary splits, metric-gated promotion.

The deployment-lifecycle subsystem (``docs/rollouts.md``): a candidate
``EngineInstance`` goes trained → SHADOW → CANARY → LIVE through a
durable :class:`~predictionio_tpu.storage.metadata.RolloutPlan` state
machine, with auto-rollback at any stage when the promotion gates
(error-rate delta, p99 delta, shadow divergence — evaluated over
sliding windows of the obs-plane metrics) fail.

- :mod:`.plan` — gate config, deterministic sticky splits, divergence
- :mod:`.controller` — sliding windows + promote/hold/rollback verdicts
- :mod:`.manager` — the query server's lifecycle driver
"""

from .controller import RolloutController, VariantWindow
from .manager import RolloutError, RolloutManager
from .plan import (
    BASELINE,
    CANDIDATE,
    GateConfig,
    prediction_divergence,
    sticky_key,
    variant_for_key,
)

__all__ = [
    "BASELINE",
    "CANDIDATE",
    "GateConfig",
    "RolloutController",
    "RolloutError",
    "RolloutManager",
    "VariantWindow",
    "prediction_divergence",
    "sticky_key",
    "variant_for_key",
]
