"""Deterministic crash-consistency simulator.

The correctness twin of ``testing/faults.py``: faults.py proves the
online path survives *network* failure; this module proves the storage
plane survives *process/power* failure. It interposes on the file
mutations a workload performs under one root directory, then enumerates
the directory states a crash could have left behind, so a test can
assert recovery invariants over every one of them ("old value or new
value, never garbage" — ``tests/test_crash_consistency.py``).

Crash model (deliberately adversarial, strictly deterministic):

1. **Prefix cuts** — the crash happens between any two recorded
   mutations: states ``ops[0:k]`` for every ``k``. This models a plain
   process kill (page cache survives, so completed writes persist).
2. **Unsynced data loss** — for each cut, any *individual* write whose
   file was never ``fsync``'d between the write and the cut may have
   lost a suffix of its data (truncated to 0, half, len-1 bytes) while
   **later metadata ops — including ``os.replace`` — still applied**.
   This is the power-loss reordering that makes write-then-rename
   without fsync a torn-blob bug: the rename's metadata journals before
   the data blocks hit disk (the ``robust-rename-no-fsync`` lint rule's
   failure mode, ``utils/durability.py``).

States are deduplicated by content, so tests iterate a bounded set.
Single-victim truncation (one lossy write per state) keeps enumeration
linear; it is enough to catch every ordering bug a single missing fsync
can cause.

Usage::

    sim = CrashSim()
    with sim.record(root):
        workload(root)              # plain open/os.replace/np.savez/...
    for state in sim.crash_states():
        crashed = state.materialize(fresh_dir())
        assert recovery_invariant(crashed)

Interposition covers Python-level file I/O (``open``/``io.open``,
``os.replace``/``rename``/``remove``/``mkdir``/``rmdir``/``fsync``/
``fdatasync``/``os.open``, and ``shutil.rmtree`` which is swapped for a
recorded re-implementation). Writers that mutate files from C
(**SQLite**) are invisible to the interposer — for those, use
**snapshot mode**: call :meth:`CrashSim.mark` at each commit boundary
and iterate :meth:`snapshot_states`; that asserts old-or-new across
boundaries, leaning on SQLite's own journal for sub-commit atomicity.
"""

from __future__ import annotations

import builtins
import contextlib
import dataclasses
import hashlib
import io
import os
import shutil
from typing import Dict, Iterator, List, Optional, Set, Tuple

__all__ = ["CrashSim", "CrashState"]


@dataclasses.dataclass
class _Op:
    kind: str  # write | trunc | fsync | replace | remove | mkdir | rmdir
    path: str = ""  # root-relative
    path2: str = ""  # replace destination
    offset: int = 0
    data: bytes = b""
    fid: int = -1  # file identity (stable across rename)


@dataclasses.dataclass
class _Tree:
    files: Dict[str, bytes]
    dirs: Set[str]


def _snapshot_tree(root: str) -> _Tree:
    files: Dict[str, bytes] = {}
    dirs: Set[str] = set()
    for dirpath, dirnames, filenames in os.walk(root):
        rel = os.path.relpath(dirpath, root)
        if rel != ".":
            dirs.add(rel)
        for name in filenames:
            path = os.path.join(dirpath, name)
            with io.open(path, "rb") as fh:  # the *real* open when patched
                files[os.path.relpath(path, root)] = fh.read()
    return _Tree(files, dirs)


class _RecordingFile:
    """Write-mode file proxy: records each ``write`` as (path, offset,
    bytes). Binary offsets come from ``tell()`` (seek-safe — zipfile's
    header backpatching is captured exactly); text mode keeps a byte
    counter (sequential writers only, which is all the package has)."""

    def __init__(self, sim: "CrashSim", fh, rel: str, fid: int, binary: bool,
                 append: bool):
        self._sim = sim
        self._fh = fh
        self._rel = rel
        self._fid = fid
        self._binary = binary
        # O_APPEND files report tell()==0 until the first write, and all
        # writes land at EOF regardless of seeks — track the append
        # cursor explicitly from the size at open.
        self._pos = None
        if append or not binary:
            try:
                self._pos = os.fstat(fh.fileno()).st_size if append else 0
            except (OSError, AttributeError):
                self._pos = 0

    def write(self, data):
        if self._binary:
            encoded = bytes(data)
            offset = self._pos if self._pos is not None else self._fh.tell()
        else:
            encoded = data.encode(self._fh.encoding or "utf-8")
            offset = self._pos
        n = self._fh.write(data)
        if self._pos is not None:
            self._pos += len(encoded)
        self._sim._record(
            _Op("write", self._rel, offset=offset, data=encoded,
                fid=self._fid)
        )
        return n

    def writelines(self, lines) -> None:
        for line in lines:
            self.write(line)

    def fileno(self) -> int:
        fd = self._fh.fileno()
        self._sim._fd_fids[fd] = self._fid
        return fd

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        # drop the fd→fid mapping before the kernel recycles the number
        try:
            self._sim._fd_fids.pop(self._fh.fileno(), None)
        except (OSError, ValueError):
            pass
        self._fh.close()

    def __getattr__(self, name):
        return getattr(self._fh, name)

    def __iter__(self):
        return iter(self._fh)


class CrashState:
    """One reconstructible post-crash directory state."""

    def __init__(
        self,
        baseline: _Tree,
        ops: List[_Op],
        cut: int,
        lost: Optional[Dict[int, int]] = None,
    ):
        self._baseline = baseline
        self._ops = ops
        self.cut = cut
        self.lost = lost or {}

    def describe(self) -> str:
        return f"cut={self.cut} lost={self.lost or '{}'}"

    def tree(self) -> _Tree:
        files = dict(self._baseline.files)
        dirs = set(self._baseline.dirs)
        for i, op in enumerate(self._ops[: self.cut]):
            if op.kind == "write":
                data = op.data
                if i in self.lost:
                    data = data[: self.lost[i]]
                buf = bytearray(files.get(op.path, b""))
                if len(buf) < op.offset:
                    buf.extend(b"\0" * (op.offset - len(buf)))
                buf[op.offset : op.offset + len(data)] = data
                files[op.path] = bytes(buf)
            elif op.kind == "trunc":
                files[op.path] = b""
            elif op.kind == "replace":
                if op.path in files:
                    files[op.path2] = files.pop(op.path)
            elif op.kind == "remove":
                files.pop(op.path, None)
            elif op.kind == "mkdir":
                dirs.add(op.path)
            elif op.kind == "rmdir":
                dirs.discard(op.path)
            # fsync: durability marker only, no state change
        return _Tree(files, dirs)

    def digest(self) -> str:
        tree = self.tree()
        h = hashlib.sha256()
        for path in sorted(tree.files):
            h.update(path.encode())
            h.update(b"\0")
            h.update(hashlib.sha256(tree.files[path]).digest())
        for d in sorted(tree.dirs):
            h.update(b"D")
            h.update(d.encode())
        return h.hexdigest()

    def materialize(self, target_dir: str) -> str:
        """Write this state under ``target_dir`` (created, must be empty
        or absent) and return it."""
        tree = self.tree()
        os.makedirs(target_dir, exist_ok=True)
        for d in sorted(tree.dirs):
            os.makedirs(os.path.join(target_dir, d), exist_ok=True)
        for rel, data in tree.files.items():
            path = os.path.join(target_dir, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with io.open(path, "wb") as fh:
                fh.write(data)
        return target_dir


class _SnapshotState(CrashState):
    def __init__(self, tree: _Tree):
        super().__init__(tree, [], 0)

    def describe(self) -> str:
        return "snapshot"


class CrashSim:
    """Recorder + crash-state enumerator. One instance, one workload."""

    _PATCHED = (
        "fsync", "fdatasync", "replace", "rename", "remove", "unlink",
        "mkdir", "rmdir", "open",
    )

    def __init__(self):
        self.ops: List[_Op] = []
        self._baseline: Optional[_Tree] = None
        self._root: Optional[str] = None
        self._fids: Dict[str, int] = {}
        self._next_fid = 0
        self._fd_fids: Dict[int, int] = {}
        self._marks: List[_Tree] = []

    # -- recording machinery ---------------------------------------------
    def _record(self, op: _Op) -> None:
        self.ops.append(op)

    def _rel(self, path) -> Optional[str]:
        try:
            abspath = os.path.abspath(os.fspath(path))
        except TypeError:
            return None
        root = self._root
        if root is None or not abspath.startswith(root + os.sep):
            return None
        return os.path.relpath(abspath, root)

    def _fid(self, rel: str, fresh: bool = False) -> int:
        if fresh or rel not in self._fids:
            self._fids[rel] = self._next_fid
            self._next_fid += 1
        return self._fids[rel]

    @contextlib.contextmanager
    def record(self, root: str) -> Iterator["CrashSim"]:
        """Interpose on file mutations under ``root`` for the duration.
        Single-threaded workloads only (the interposition is global)."""
        self._root = os.path.abspath(root)
        os.makedirs(self._root, exist_ok=True)
        self._baseline = _snapshot_tree(self._root)
        real = {
            "open": builtins.open,
            "os_open": os.open,
            "os_close": os.close,
            "fsync": os.fsync,
            "fdatasync": os.fdatasync,
            "replace": os.replace,
            "rename": os.rename,
            "remove": os.remove,
            "unlink": os.unlink,
            "mkdir": os.mkdir,
            "rmdir": os.rmdir,
            "rmtree": shutil.rmtree,
        }
        sim = self

        def patched_open(file, mode="r", *args, **kwargs):
            rel = sim._rel(file) if not isinstance(file, int) else None
            writable = any(c in mode for c in "wax+")
            fh = real["open"](file, mode, *args, **kwargs)
            if rel is None or not writable:
                return fh
            fresh = "w" in mode or "x" in mode
            fid = sim._fid(rel, fresh=fresh)
            if fresh:
                sim._record(_Op("trunc", rel, fid=fid))
            return _RecordingFile(
                sim, fh, rel, fid, binary="b" in mode, append="a" in mode
            )

        def patched_os_open(path, flags, *args, **kwargs):
            fd = real["os_open"](path, flags, *args, **kwargs)
            rel = sim._rel(path)
            if rel is not None:
                sim._fd_fids[fd] = sim._fid(rel)
            return fd

        def patched_os_close(fd):
            sim._fd_fids.pop(fd, None)  # fd numbers recycle
            real["os_close"](fd)

        def patched_fsync(fd):
            real["fsync"](fd)
            fid = sim._fd_fids.get(fd)
            if fid is not None:
                sim._record(_Op("fsync", fid=fid))

        def patched_fdatasync(fd):
            real["fdatasync"](fd)
            fid = sim._fd_fids.get(fd)
            if fid is not None:
                sim._record(_Op("fsync", fid=fid))

        def patched_replace(src, dst, **kwargs):
            real["replace"](src, dst, **kwargs)
            rel_src, rel_dst = sim._rel(src), sim._rel(dst)
            if rel_src is not None and rel_dst is not None:
                if rel_src in sim._fids:
                    sim._fids[rel_dst] = sim._fids.pop(rel_src)
                sim._record(_Op("replace", rel_src, path2=rel_dst))

        def patched_remove(path, **kwargs):
            real["remove"](path, **kwargs)
            rel = sim._rel(path)
            if rel is not None and "dir_fd" not in kwargs:
                sim._fids.pop(rel, None)
                sim._record(_Op("remove", rel))

        def patched_mkdir(path, *args, **kwargs):
            real["mkdir"](path, *args, **kwargs)
            rel = sim._rel(path)
            if rel is not None:
                sim._record(_Op("mkdir", rel))

        def patched_rmdir(path, **kwargs):
            real["rmdir"](path, **kwargs)
            rel = sim._rel(path)
            if rel is not None and "dir_fd" not in kwargs:
                sim._record(_Op("rmdir", rel))

        def patched_rmtree(path, ignore_errors=False, onerror=None, **kw):
            # re-implemented over the patched os hooks: the stdlib's
            # fd-relative fast path would bypass recording entirely
            try:
                for dirpath, dirnames, filenames in os.walk(
                    path, topdown=False
                ):
                    for name in sorted(filenames):
                        patched_remove(os.path.join(dirpath, name))
                    patched_rmdir(dirpath)
            except OSError:
                if not ignore_errors:
                    raise

        try:
            builtins.open = patched_open
            io.open = patched_open
            os.open = patched_os_open
            os.close = patched_os_close
            os.fsync = patched_fsync
            os.fdatasync = patched_fdatasync
            os.replace = patched_replace
            os.rename = patched_replace
            os.remove = patched_remove
            os.unlink = patched_remove
            os.mkdir = patched_mkdir
            os.rmdir = patched_rmdir
            shutil.rmtree = patched_rmtree
            yield self
        finally:
            builtins.open = real["open"]
            io.open = real["open"]
            os.open = real["os_open"]
            os.close = real["os_close"]
            os.fsync = real["fsync"]
            os.fdatasync = real["fdatasync"]
            os.replace = real["replace"]
            os.rename = real["rename"]
            os.remove = real["remove"]
            os.unlink = real["unlink"]
            os.mkdir = real["mkdir"]
            os.rmdir = real["rmdir"]
            shutil.rmtree = real["rmtree"]

    # -- enumeration ------------------------------------------------------
    def _synced_spans(self) -> Dict[int, List[int]]:
        """fid -> sorted op indices of its fsyncs."""
        spans: Dict[int, List[int]] = {}
        for i, op in enumerate(self.ops):
            if op.kind == "fsync":
                spans.setdefault(op.fid, []).append(i)
        return spans

    def crash_states(self) -> List[CrashState]:
        """Every reconstructible crash state, content-deduplicated."""
        if self._baseline is None:
            raise RuntimeError("crash_states() before record()")
        syncs = self._synced_spans()

        def synced_by(i: int, k: int) -> bool:
            return any(i < j < k for j in syncs.get(self.ops[i].fid, ()))

        states: List[CrashState] = []
        seen: Set[str] = set()

        def add(cut: int, lost: Optional[Dict[int, int]] = None) -> None:
            state = CrashState(self._baseline, self.ops, cut, lost)
            digest = state.digest()
            if digest not in seen:
                seen.add(digest)
                states.append(state)

        n = len(self.ops)
        for k in range(n + 1):
            add(k)
            for i in range(k):
                op = self.ops[i]
                if op.kind != "write" or not op.data:
                    continue
                if synced_by(i, k):
                    continue
                size = len(op.data)
                for trunc in sorted({0, size // 2, size - 1}):
                    if trunc < size:
                        add(k, {i: trunc})
        return states

    # -- snapshot mode (opaque writers: SQLite) ---------------------------
    def mark(self, root: str) -> None:
        """Snapshot ``root`` at a consistency boundary (e.g. after each
        commit). For writers whose I/O the interposer cannot see."""
        self._marks.append(_snapshot_tree(os.path.abspath(root)))

    def snapshot_states(self) -> List[CrashState]:
        return [_SnapshotState(tree) for tree in self._marks]
