"""Injectable fake monotonic clock for deterministic drives.

Every plane with time-based decisions (resilience, rollout gates, the
continuous controller) takes an injected ``clock`` callable; this is the
one shared advanceable implementation — tests and the deterministic
loadgen scenarios use it instead of each growing a private copy.
"""

from __future__ import annotations

__all__ = ["FakeClock"]


class FakeClock:
    """A monotonic clock that only moves when told to."""

    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds
