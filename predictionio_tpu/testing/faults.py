"""Deterministic fault-injection harness for the online data plane.

The resilience layer (``utils/resilience.py``) is only trustworthy if
its failure paths are *exercised*, and real network failures are
non-deterministic by nature. This harness inverts that: production code
marks its I/O boundaries with :func:`fault_point` calls (a no-op
``None``-check when no plan is active), and tests — or a chaos run
against a live server — activate a plan that makes those boundaries
fail in precisely scripted ways.

Fault kinds (the classic dependency-failure repertoire):

- ``refuse``       raise ``ConnectionRefusedError`` (dependency down)
- ``close``        raise ``http.client.RemoteDisconnected`` (the
                   mid-stream / stale-keep-alive socket-close signature;
                   subclasses ``ConnectionResetError``)
- ``reset``        raise ``ConnectionResetError`` (peer RST mid-transfer)
- ``latency:<ms>`` inject ``<ms>`` of delay (through the injectable
                   ``sleep`` so even latency faults need no wall clock)

Every kind takes an optional ``*N`` multiplier: fire on the first N
matching hits, then stop — i.e. **N-failures-then-ok**, the shape every
retry/breaker test needs. Without ``*N`` the fault fires on every hit.

Activation:

- **programmatic** (tests): ``with faults.inject(FaultSpec(...)): ...``
  or ``faults.activate(...)`` / ``faults.deactivate()``.
- **env-var** (live servers, ``tools/loadgen.py --fault``): set
  ``PIO_FAULTS`` before the server starts, e.g. ::

      PIO_FAULTS="serving.feedback=refuse*3;remote.send=latency:50"

Known sites (grep ``fault_point(`` for the live list):

- ``remote.send``        storage client, just before the request goes
                         on the wire (info: ``method``, ``url``,
                         ``fresh``, ``idempotent``)
- ``serving.feedback``   query server → Event Server feedback POST
- ``serving.error_log``  query server → ``--log-url`` error POST
- ``serving.predict``    query server, just before the predict dispatch
                         (``loadgen --brownout`` wedges it with latency
                         and refusals — docs/slo.md)
- ``serving.candidate``  candidate-variant serve (``loadgen --rollout``)

Determinism: per-spec hit counters under one lock; no randomness, no
wall-clock reads. The harness is stdlib-only, like everything else on
the storage/serving import path.
"""

from __future__ import annotations

import dataclasses
import http.client
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

__all__ = [
    "FaultSpec",
    "FaultInjector",
    "activate",
    "deactivate",
    "active",
    "fault_point",
    "inject",
    "parse",
]

_KINDS = ("refuse", "close", "reset", "latency")


@dataclasses.dataclass
class FaultSpec:
    """One scripted fault: fire ``kind`` at ``site``, ``times`` times
    (``None`` = every hit). ``when`` optionally filters on the call
    site's keyword info (e.g. only non-fresh connections)."""

    site: str
    kind: str
    arg: float = 0.0  # latency ms for kind="latency"
    times: Optional[int] = None
    when: Optional[Callable[[Dict[str, Any]], bool]] = None

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of {_KINDS}"
            )


def parse(text: str) -> List[FaultSpec]:
    """``site=kind[:arg][*times][;site=kind...]`` → specs. The format of
    ``PIO_FAULTS`` and ``loadgen --fault``."""
    specs: List[FaultSpec] = []
    for chunk in text.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        try:
            site, rhs = chunk.split("=", 1)
        except ValueError:
            raise ValueError(
                f"bad fault spec {chunk!r}: expected site=kind[:arg][*times]"
            ) from None
        times: Optional[int] = None
        if "*" in rhs:
            rhs, times_s = rhs.rsplit("*", 1)
            times = int(times_s)
        arg = 0.0
        if ":" in rhs:
            rhs, arg_s = rhs.split(":", 1)
            arg = float(arg_s)
        specs.append(
            FaultSpec(site=site.strip(), kind=rhs.strip(), arg=arg,
                      times=times)
        )
    return specs


class FaultInjector:
    """The active fault plan: matches sites, counts hits, fires faults."""

    def __init__(
        self,
        specs: Sequence[FaultSpec],
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._specs = list(specs)
        self._sleep = sleep
        self._lock = threading.Lock()
        self._fired: Dict[int, int] = {}  # spec index -> times fired
        self._hits: Dict[str, int] = {}  # site -> times reached (any spec)

    def fired(self, site: Optional[str] = None) -> int:
        """How many faults actually fired (optionally at one site)."""
        with self._lock:
            if site is None:
                return sum(self._fired.values())
            return sum(
                count
                for idx, count in self._fired.items()
                if self._specs[idx].site == site
            )

    def hits(self, site: str) -> int:
        """How many times ``site`` was reached while this plan was
        active (fired or not) — the 'did production code actually route
        through the harness' assertion."""
        with self._lock:
            return self._hits.get(site, 0)

    def fire(self, site: str, info: Dict[str, Any]) -> None:
        to_fire: Optional[FaultSpec] = None
        with self._lock:
            self._hits[site] = self._hits.get(site, 0) + 1
            for idx, spec in enumerate(self._specs):
                if spec.site != site:
                    continue
                if spec.when is not None and not spec.when(info):
                    continue
                if (
                    spec.times is not None
                    and self._fired.get(idx, 0) >= spec.times
                ):
                    continue  # budget exhausted: N-failures-then-ok
                self._fired[idx] = self._fired.get(idx, 0) + 1
                to_fire = spec
                break
        if to_fire is None:
            return
        if to_fire.kind == "refuse":
            raise ConnectionRefusedError(
                f"[injected] connection refused at {site}"
            )
        if to_fire.kind == "close":
            raise http.client.RemoteDisconnected(
                f"[injected] server closed connection at {site}"
            )
        if to_fire.kind == "reset":
            raise ConnectionResetError(f"[injected] connection reset at {site}")
        if to_fire.kind == "latency":
            self._sleep(to_fire.arg / 1000.0)


# -- module-level activation --------------------------------------------------

_injector: Optional[FaultInjector] = None
_activation_lock = threading.Lock()


def activate(
    *specs: FaultSpec, sleep: Callable[[float], None] = time.sleep
) -> FaultInjector:
    """Install a fault plan process-wide (replacing any active one)."""
    global _injector
    with _activation_lock:
        _injector = FaultInjector(specs, sleep=sleep)
        return _injector


def deactivate() -> None:
    global _injector
    with _activation_lock:
        _injector = None


def active() -> Optional[FaultInjector]:
    return _injector


def fault_point(site: str, **info: Any) -> None:
    """The production-side hook: a no-op unless a plan is active.

    Placed at I/O boundaries so an injected ``ConnectionRefusedError``
    (etc.) flows through exactly the ``except`` clauses a real one
    would."""
    injector = _injector
    if injector is not None:
        injector.fire(site, info)


class inject:
    """``with faults.inject(spec, ...) as plan:`` — scoped activation."""

    def __init__(
        self, *specs: FaultSpec, sleep: Callable[[float], None] = time.sleep
    ):
        self._specs = specs
        self._sleep = sleep
        self.plan: Optional[FaultInjector] = None

    def __enter__(self) -> FaultInjector:
        self.plan = activate(*self._specs, sleep=self._sleep)
        return self.plan

    def __exit__(self, *exc: Any) -> None:
        deactivate()


def _install_from_env() -> None:
    """Env activation for live servers: ``PIO_FAULTS`` set in a server's
    environment arms the harness at import time (the ``loadgen --fault``
    cookbook in docs/robustness.md)."""
    text = os.environ.get("PIO_FAULTS", "")
    if text:
        activate(*parse(text))


_install_from_env()
