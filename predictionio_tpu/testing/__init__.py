"""Test-support plane: deterministic fault injection for the online path.

Production modules route their network I/O through
:func:`predictionio_tpu.testing.faults.fault_point` call sites; this
package turns those sites into controllable failure points in tests and
chaos runs while costing one ``None``-check in production.
"""

from .clock import FakeClock
from .faults import FaultSpec, activate, deactivate, fault_point, inject, parse

__all__ = [
    "FakeClock",
    "FaultSpec",
    "activate",
    "deactivate",
    "fault_point",
    "inject",
    "parse",
]
