"""Profiling hooks: phase timers + device traces.

The reference's observability is request counters on the serving page and
the Spark UI for everything else (SURVEY §5 "Tracing / profiling"). Here
every workflow run carries a :class:`StepTimer` (phase wall-clock, exposed
in logs and queryable from the context), and :func:`device_trace` wraps
``jax.profiler.trace`` so a run can emit a TensorBoard-loadable device
profile with one env var (``PIO_PROFILE_DIR``).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional


class StepTimer:
    """Accumulates named phase timings (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: Dict[str, list] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self._records.setdefault(name, []).append(float(seconds))

    @contextlib.contextmanager
    def time(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "count": len(vals),
                    "total_s": sum(vals),
                    "mean_s": sum(vals) / len(vals),
                    "max_s": max(vals),
                }
                for name, vals in self._records.items()
                if vals
            }

    def format_summary(self) -> str:
        parts = [
            f"{name}: {s['total_s']:.3f}s"
            + (f" ({s['count']}x, mean {s['mean_s']:.3f}s)" if s["count"] > 1 else "")
            for name, s in sorted(self.summary().items())
        ]
        return "; ".join(parts) or "(no phases recorded)"


# -- persisted phase summaries (docs/observability.md) ----------------------
#
# A StepTimer dies with its process; the training workflow persists its
# summary into the completed engine instance's env map under this key so
# per-phase timings survive to the serving/status plane (the query
# server re-exports them as pio_train_phase_seconds gauges, and the
# dashboard's /engine_instances listing renders them).

TRAIN_PHASES_ENV_KEY = "PIO_TRAIN_PHASES"


def phases_to_env(summary: Dict[str, Dict[str, float]]) -> str:
    """``StepTimer.summary()`` → the compact JSON stored in the engine
    instance env (phase → total seconds)."""
    import json

    return json.dumps(
        {name: round(s["total_s"], 6) for name, s in sorted(summary.items())}
    )


def phases_from_env(env: Optional[Dict[str, str]]) -> Dict[str, float]:
    """Inverse of :func:`phases_to_env`; {} on absence or garbage (an old
    instance record must not break the status page)."""
    import json

    raw = (env or {}).get(TRAIN_PHASES_ENV_KEY)
    if not raw:
        return {}
    try:
        parsed = json.loads(raw)
        return {
            str(k): float(v)
            for k, v in parsed.items()
            if isinstance(v, (int, float))
        }
    except (ValueError, AttributeError):
        return {}


# -- persisted compile/retrace profile (docs/observability.md#profiling) ----
#
# Same mechanism as PIO_TRAIN_PHASES, richer payload: run_train persists
# the jit-telemetry delta of the run (per-fn compiles/retraces/compile
# seconds + compilation-cache hits/misses) so `pio profile` can report a
# COMPLETED instance's compile behavior long after the process died.

TRAIN_PROFILE_ENV_KEY = "PIO_TRAIN_PROFILE"


def profile_to_env(snapshot: Dict) -> str:
    """JSON-safe profile snapshot (``JitTelemetry.delta_since`` shape,
    optionally with a ``phases`` key) → the instance-env string."""
    import json

    return json.dumps(snapshot, sort_keys=True)


def profile_from_env(env: Optional[Dict[str, str]]) -> Dict:
    """Inverse of :func:`profile_to_env`; {} on absence or garbage (an
    old instance record must not break `pio profile`)."""
    import json

    raw = (env or {}).get(TRAIN_PROFILE_ENV_KEY)
    if not raw:
        return {}
    try:
        parsed = json.loads(raw)
        return parsed if isinstance(parsed, dict) else {}
    except ValueError:
        return {}


@contextlib.contextmanager
def device_trace(logdir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` wrapper: no-op when ``logdir`` is falsy or the
    profiler is unavailable; otherwise writes a TensorBoard trace."""
    if not logdir:
        yield
        return
    try:
        import jax.profiler as profiler
    except Exception:
        yield
        return
    with profiler.trace(logdir):
        yield
