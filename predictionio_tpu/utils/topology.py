"""Deviceless (compile-only) TPU topology access, with lockfile retry.

``jax.experimental.topologies.get_topology_desc`` loads libtpu, which
holds a machine-wide lockfile during plugin init — a concurrent device
probe, prewarm run, or test session makes the first attempt fail
transiently. Every in-repo user (``tools/prewarm_cache``, the Mosaic
AOT test modules) goes through this helper so they all share the retry
(full-jittered via the shared :class:`RetryPolicy`: the contenders are
exactly the processes that would otherwise wake in lockstep and collide
on the lockfile again).

Argument-format note (cost a whole round to discover):
``chips_per_host_bounds`` must be a TUPLE OF INTS, e.g. ``(1, 1, 1)``;
string forms are rejected by libtpu with a mangled type error.
"""

from __future__ import annotations

from .resilience import RetryPolicy


def get_deviceless_topology(name: str, retries: int = 1,
                            retry_delay_s: float = 10.0, **kwargs):
    """A compile-only TPU topology (e.g. ``"v5e:1x1"`` with
    ``chips_per_host_bounds=(1, 1, 1)``, or ``"v5e:2x2"``). Retries
    libtpu-lockfile contention ``retries`` times; any other failure
    (no libtpu at all) raises immediately."""
    from jax.experimental import topologies

    policy = RetryPolicy(
        attempts=retries + 1,
        base_delay_s=retry_delay_s,
        max_delay_s=retry_delay_s * 2,
    )
    return policy.call(
        lambda: topologies.get_topology_desc(name, "tpu", **kwargs),
        should_retry=lambda exc: "lockfile" in str(exc),
    )
