"""Shared file-durability helpers.

Every write-then-rename site in the package must follow the same
discipline (enforced by the ``robust-rename-no-fsync`` lint rule): flush
and fsync the temporary file *before* ``os.replace``, then fsync the
parent directory so the new directory entry itself is durable. Skipping
the first fsync is the classic torn-blob bug — on many filesystems the
rename's metadata can be journaled before the file's data blocks are
written, so a power loss leaves a durable *name* pointing at truncated
or empty bytes. This module is the single home for that sequence.
"""

from __future__ import annotations

import os


def fsync_file(path: str) -> None:
    """fsync an existing file by path (data written by someone else, e.g.
    a compiler subprocess)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """fsync a directory so newly-created/renamed entries are durable
    (no-op on platforms that disallow opening directories)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Crash-safe whole-file replace: write to a sibling temp file, fsync
    it, rename over ``path``, fsync the parent directory. After a crash
    at any point, ``path`` holds either the complete old bytes or the
    complete new bytes — never a torn mix."""
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))
