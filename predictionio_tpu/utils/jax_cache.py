"""Persistent JAX compilation cache shared across processes.

The revalidation queue runs every device step as a fresh subprocess — by
design, so a tunnel wedge is a recorded timeout rather than a dead queue
(``tools/tpu_revalidate.py``). The cost of that isolation used to be that
each of the queue's ~10 legs re-paid full XLA/Mosaic compilation of
largely identical programs *inside* a historically scarce hardware
window: the round-2 evidence shows a 2.67 s compile in iteration 1 per
bench process, and the deploy-path serving compiles (one per pipeline
depth per engine in the loadgen sweep) are larger. JAX's persistent
compilation cache stores compiled executables on disk keyed by
(program HLO, backend, compiler options) and re-loads them in any later
process, so the second and subsequent subprocesses start warm.

The reference has no analogue to point at — its equivalent cost is JVM +
Spark warmup, re-paid on every ``spark-submit`` child
(``tools/src/main/scala/io/prediction/tools/RunWorkflow.scala:103-169``);
caching the compiled program across processes is a place the TPU-native
stack can simply do better.

Env contract (documented in docs/performance.md):

- ``JAX_COMPILATION_CACHE_DIR`` — JAX's own knob; if already set it wins
  untouched, so operators can redirect the cache without learning a new
  variable.
- ``PIO_JAX_CACHE_DIR`` — ours; overrides the default location. An
  *empty string* disables caching entirely (hermetic runs).
- default: ``/tmp/pio-jax-cache``. /tmp is volatile, but so is the
  hardware window the cache exists to protect; a cold cache merely
  reverts to today's behavior.
"""

from __future__ import annotations

import os
from typing import Optional

#: Default on-disk location; /tmp survives across the queue's subprocesses
#: and across watcher-triggered queue attempts within a boot.
DEFAULT_CACHE_DIR = "/tmp/pio-jax-cache"


def enable_compilation_cache(
    default_dir: str = DEFAULT_CACHE_DIR,
) -> Optional[str]:
    """Turn on JAX's persistent compilation cache for this process AND
    every child it spawns (via ``JAX_COMPILATION_CACHE_DIR`` env
    inheritance — deploys, CPU-fallback re-execs, and queue steps all
    launch children with ``os.environ``-derived environments).

    Must run before the first JAX compilation to help that compilation;
    safe (idempotent, best-effort) at any point. Returns the cache dir,
    or ``None`` when disabled or unavailable.
    """
    preexisting = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    cache_dir = preexisting
    if cache_dir is None:
        cache_dir = os.environ.get("PIO_JAX_CACHE_DIR", default_dir)
    if not cache_dir:
        return None
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        return None
    # Cache every program: serving-dispatch programs compile in well
    # under the 1 s default threshold, but they are exactly what the
    # loadgen sweep's per-depth deploys re-pay inside the window.
    wanted = (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    )
    applied: list = []  # (name, previous value) of updates that landed
    try:
        import jax

        for name, value in wanted:
            previous = getattr(jax.config, name, None)
            jax.config.update(name, value)
            applied.append((name, previous))
    except Exception:
        # Partial failure must not half-enable caching: roll the config
        # back to its pre-call state so this process never runs with
        # (say) the cache dir set but the thresholds still defaulted.
        for name, previous in reversed(applied):
            try:
                jax.config.update(name, previous)
            except Exception:
                pass
        # Only this function's own export (below) is ours to undo. A
        # pre-existing JAX_COMPILATION_CACHE_DIR — the operator's, or a
        # parent process's successful call — is their state: popping it
        # would silently disable caching in every child they launch.
        if preexisting is None:
            os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        return None
    # exported only after the in-process config succeeded, so children
    # (deploys, fallback re-execs, queue steps) inherit a working setup
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    # Cache observability (docs/observability.md#profiling): every
    # process that enables the cache also starts counting its hits and
    # misses (jax.monitoring events) into the process jit telemetry, so
    # /metrics and `pio profile` can answer "did the cache actually save
    # the window?" with numbers instead of vibes.
    try:
        from ..obs.profile import default_telemetry

        default_telemetry().attach_monitoring()
    except Exception:
        pass  # telemetry is an observer; it must never fail cache setup
    return cache_dir
