"""Shared resilience primitives for the online data plane.

The ROADMAP north star is serving heavy traffic from millions of users;
round 3's fault work hardened the *training* path (heartbeat fail-loud,
checkpoint resume) but the online path — query server, Event Server,
remote storage — still hung or died arbitrarily when a dependency
stalled or the offered load exceeded device throughput. This module is
the one home for the three primitives every online server shares (the
pattern the ads-serving paper in PAPERS.md makes the price of admission
at this scale):

- :class:`Deadline` — a request-scoped time budget, propagated across
  process boundaries via the ``X-PIO-Deadline-Ms`` header (*remaining*
  milliseconds, never an absolute timestamp: peer clocks are not
  comparable) and checked at every stage of a request — critically,
  *before* the MicroBatcher dispatch, so an already-expired query never
  wastes a device slot.
- :class:`RetryPolicy` — bounded retries with exponential backoff and
  **full jitter** (delay ~ U(0, min(cap, base·2^i)); constant-delay
  retries synchronize a fleet into thundering herds). Clock, sleep and
  rng are injectable so every retry schedule is testable without a
  single wall-clock sleep.
- :class:`CircuitBreaker` — closed → open after a failure threshold,
  open → half-open after a cooldown, half-open admits a bounded number
  of probe requests whose outcome closes or re-opens the circuit. The
  ALX TPU-residency model makes degradation nearly free: the last-good
  factor tables are already resident in HBM, so a serving process whose
  storage/event dependencies trip a breaker keeps answering from the
  resident model ("degraded: true") instead of dying.

Everything here is stdlib-only and device-free: the primitives must be
importable from the Event Server and storage client paths where jax may
not even be installed.

Env knobs (read by :meth:`CircuitBreaker.from_env`; see
``docs/robustness.md``):

- ``PIO_BREAKER_FAILURES``       consecutive failures to open (default 5)
- ``PIO_BREAKER_RESET_S``        open → half-open cooldown (default 30)
- ``PIO_BREAKER_HALF_OPEN_PROBES`` concurrent probes admitted half-open
  (default 1)
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading
import time
from typing import Any, Callable, Iterator, Optional, Tuple, Type

__all__ = [
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "RetryPolicy",
    "CircuitBreaker",
    "CircuitOpen",
    "current_deadline",
    "deadline_scope",
]

#: Wire header carrying a request's REMAINING budget in milliseconds.
#: Relative, not absolute: the sender computes ``remaining_ms()`` at send
#: time, so the receiver needs no clock agreement with the sender.
DEADLINE_HEADER = "X-PIO-Deadline-Ms"


class DeadlineExceeded(RuntimeError):
    """A request overran its deadline. ``stage`` names where it was
    caught (admission / dispatch / downstream), for the status counters
    and the error body."""

    def __init__(self, message: str, stage: str = ""):
        super().__init__(message)
        self.stage = stage


class Deadline:
    """A monotonic-clock expiry point with an injectable clock.

    Created from a millisecond budget (:meth:`after_ms`) or an incoming
    header (:meth:`from_header`); consumed via :meth:`check` (raise when
    expired), :meth:`remaining_s` (cap a socket timeout) and
    :meth:`header_value` (propagate downstream).
    """

    __slots__ = ("_expires_at", "_clock")

    def __init__(
        self, expires_at: float, clock: Callable[[], float] = time.monotonic
    ):
        self._expires_at = expires_at
        self._clock = clock

    @classmethod
    def after_ms(
        cls, budget_ms: float, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        return cls(clock() + budget_ms / 1000.0, clock)

    @classmethod
    def from_header(
        cls,
        value: Optional[str],
        clock: Callable[[], float] = time.monotonic,
    ) -> Optional["Deadline"]:
        """Parse an ``X-PIO-Deadline-Ms`` header. Absent or malformed →
        ``None`` (no deadline): a garbled header from a buggy client must
        degrade to today's unbounded behavior, never to a 500."""
        if value is None:
            return None
        try:
            budget_ms = float(value.strip())
        except (ValueError, AttributeError):
            return None
        if budget_ms < 0:
            budget_ms = 0.0
        return cls.after_ms(budget_ms, clock)

    def remaining_s(self) -> float:
        """Seconds left; negative when already expired."""
        return self._expires_at - self._clock()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1000.0

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0

    def check(self, stage: str) -> None:
        """Raise :class:`DeadlineExceeded` when the budget is gone —
        call at every stage boundary so an expired request stops at the
        *next* checkpoint instead of riding the whole pipeline."""
        remaining = self.remaining_s()
        if remaining <= 0:
            raise DeadlineExceeded(
                f"deadline exceeded at {stage} "
                f"({-remaining * 1000.0:.1f} ms past budget)",
                stage=stage,
            )

    def cap_timeout(self, timeout_s: float) -> float:
        """A socket timeout never longer than the remaining budget (with
        a floor: a non-positive socket timeout means 'non-blocking' to
        the stdlib, which is never what a deadline means)."""
        return max(0.001, min(timeout_s, self.remaining_s()))

    def header_value(self) -> str:
        return str(max(0, int(self.remaining_ms())))


# -- ambient propagation ------------------------------------------------------
#
# The serving request path crosses module boundaries whose signatures
# predate deadlines (engine `supplement`/`serve` hooks calling into the
# event store at query time). A context-local carries the live request's
# deadline to those depths without threading a parameter through every
# engine API. NOTE: contextvars do not cross thread boundaries, so work
# handed to the MicroBatcher's worker threads must be deadline-checked
# BEFORE submission (which the query server does).

_ambient_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "pio_deadline", default=None
)


def current_deadline() -> Optional[Deadline]:
    """The deadline of the request this thread is serving, if any."""
    return _ambient_deadline.get()


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]) -> Iterator[None]:
    """Make ``deadline`` ambient for the dynamic extent of a request."""
    token = _ambient_deadline.set(deadline)
    try:
        yield
    finally:
        _ambient_deadline.reset(token)


class RetryPolicy:
    """Bounded retries with exponential backoff and full jitter.

    ``attempts`` is the TOTAL number of tries (1 = no retry). Delay
    before retry *i* (0-based) is drawn uniformly from
    ``[0, min(max_delay_s, base_delay_s * 2**i)]`` — AWS-style full
    jitter, so a fleet of clients retrying the same dead dependency
    spreads out instead of stampeding in lockstep.

    ``rng``, ``sleep`` and ``clock`` are injectable: tests pin the rng
    and capture sleeps, so every schedule asserts deterministically with
    zero wall-clock cost.

    ``on_retry`` (optional) fires once per retry actually taken, with
    the 0-based retry index — the observability hook the serving metrics
    use to count retries without wrapping every call site
    (``docs/observability.md``). It must not raise.
    """

    def __init__(
        self,
        attempts: int = 3,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        retry_on: Tuple[Type[BaseException], ...] = (Exception,),
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        on_retry: Optional[Callable[[int], None]] = None,
    ):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        self.attempts = attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.retry_on = retry_on
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._clock = clock
        self._on_retry = on_retry

    def delay_for(self, retry_index: int) -> float:
        """The (jittered) delay before retry ``retry_index`` (0-based)."""
        cap = min(self.max_delay_s, self.base_delay_s * (2 ** retry_index))
        return self._rng.uniform(0.0, cap)

    def call(
        self,
        fn: Callable[[], Any],
        should_retry: Optional[Callable[[BaseException], bool]] = None,
        deadline: Optional[Deadline] = None,
    ) -> Any:
        """Run ``fn`` under the policy.

        Retries only exceptions matching ``retry_on`` (and, when given,
        the ``should_retry`` predicate — e.g. "lockfile contention only").
        A live ``deadline`` bounds the whole schedule: no retry is
        attempted once the budget cannot cover its backoff delay."""
        last: Optional[BaseException] = None
        for attempt in range(self.attempts):
            if deadline is not None and attempt > 0:
                deadline.check("retry")
            try:
                return fn()
            except self.retry_on as exc:
                if should_retry is not None and not should_retry(exc):
                    raise
                last = exc
                if attempt == self.attempts - 1:
                    raise
                delay = self.delay_for(attempt)
                if deadline is not None and deadline.remaining_s() <= delay:
                    raise  # the budget can't cover the backoff: fail now
                if self._on_retry is not None:
                    self._on_retry(attempt)
                self._sleep(delay)
        raise last  # pragma: no cover — loop always returns or raises


class CircuitOpen(RuntimeError):
    """Fast-fail: the protected dependency's circuit is open. Carries
    ``retry_after_s`` so callers (and HTTP 503 responses) can surface a
    meaningful Retry-After."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """Classic three-state circuit breaker with probe-limited half-open.

    - **closed**: calls flow; ``failure_threshold`` *consecutive*
      failures open the circuit.
    - **open**: calls raise :class:`CircuitOpen` instantly (no socket
      work, no timeout wait) until ``reset_timeout_s`` has elapsed.
    - **half-open**: up to ``half_open_probes`` in-flight probe calls
      are admitted; a probe success closes the circuit, a probe failure
      re-opens it (and restarts the cooldown).

    Thread-safe; the clock is injectable so open→half-open transitions
    are testable without waiting out a cooldown.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    #: numeric encoding for the metrics plane: a breaker-state *gauge*
    #: must be orderable (alert on > 0) — 0 closed, 1 half-open, 2 open
    STATE_VALUES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(
        self,
        name: str = "",
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_probes = max(1, half_open_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._open_count = 0  # lifetime open transitions (status page)
        self._probes_in_flight = 0

    @classmethod
    def from_env(
        cls,
        name: str,
        env: Optional[dict] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> "CircuitBreaker":
        env = os.environ if env is None else env
        return cls(
            name=name,
            failure_threshold=int(env.get("PIO_BREAKER_FAILURES", "5")),
            reset_timeout_s=float(env.get("PIO_BREAKER_RESET_S", "30")),
            half_open_probes=int(env.get("PIO_BREAKER_HALF_OPEN_PROBES", "1")),
            clock=clock,
        )

    # -- state machine ----------------------------------------------------
    def before_call(self) -> None:
        """Admission check; raises :class:`CircuitOpen` when the call
        must not be attempted. Admitted half-open calls are counted as
        probes until their success/failure is recorded."""
        with self._lock:
            if self._state == self.OPEN:
                elapsed = self._clock() - self._opened_at
                if elapsed < self.reset_timeout_s:
                    raise CircuitOpen(
                        f"circuit {self.name or '(anonymous)'} open; "
                        f"retry in {self.reset_timeout_s - elapsed:.1f}s",
                        retry_after_s=self.reset_timeout_s - elapsed,
                    )
                self._state = self.HALF_OPEN
                self._probes_in_flight = 0
            if self._state == self.HALF_OPEN:
                if self._probes_in_flight >= self.half_open_probes:
                    raise CircuitOpen(
                        f"circuit {self.name or '(anonymous)'} half-open; "
                        "probe already in flight",
                        retry_after_s=self.reset_timeout_s,
                    )
                self._probes_in_flight += 1

    def record_success(self) -> None:
        closed = False
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probes_in_flight = max(0, self._probes_in_flight - 1)
                self._state = self.CLOSED
                closed = True
            self._consecutive_failures = 0
        if closed:
            self._flight("closed")

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            if self._state == self.HALF_OPEN:
                # a failed probe re-opens immediately: the dependency is
                # still down, restart the cooldown
                self._probes_in_flight = max(0, self._probes_in_flight - 1)
                self._trip()
                tripped = True
            else:
                self._consecutive_failures += 1
                if (
                    self._state == self.CLOSED
                    and self._consecutive_failures >= self.failure_threshold
                ):
                    self._trip()
                    tripped = True
        if tripped:
            self._flight("open")

    def _trip(self) -> None:  # caller holds the lock
        self._state = self.OPEN
        self._opened_at = self._clock()
        self._open_count += 1
        self._consecutive_failures = 0

    def _flight(self, to_state: str) -> None:
        """Breaker transitions are exactly the events a post-mortem
        needs on the timeline — tap the process flight recorder
        (docs/slo.md), OUTSIDE the breaker lock, best-effort (a
        forensics fault must never affect the breaker)."""
        try:
            from ..obs.flight import record

            record(
                "breaker", f"breaker.{self.name or 'anonymous'}",
                state=to_state, opens=self._open_count,
            )
        except Exception:
            pass

    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        """Run ``fn`` under the breaker: admission check, then outcome
        recording. One ``call`` is one logical operation — wrap the
        *whole* retried attempt in it, so a retry schedule that
        eventually succeeds counts as a success, not N-1 failures."""
        self.before_call()
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result

    # -- introspection ----------------------------------------------------
    @property
    def state(self) -> str:
        """Current state, with the open→half-open time transition applied
        (so a status page polled after the cooldown reads "half-open",
        matching what the next call would experience)."""
        with self._lock:
            if (
                self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s
            ):
                return self.HALF_OPEN
            return self._state

    @property
    def state_value(self) -> int:
        """:attr:`state` as its gauge encoding (0/1/2)."""
        return self.STATE_VALUES[self.state]

    @property
    def open_count(self) -> int:
        """Lifetime closed→open transitions (monotonic — exposed as the
        ``pio_breaker_opens`` gauge)."""
        with self._lock:
            return self._open_count

    def snapshot(self) -> dict:
        """Status-page JSON shape."""
        state = self.state
        with self._lock:
            out = {
                "state": state,
                "consecutiveFailures": self._consecutive_failures,
                "openCount": self._open_count,
            }
            if self._state == self.OPEN:
                out["retryAfterS"] = round(
                    max(
                        0.0,
                        self.reset_timeout_s
                        - (self._clock() - self._opened_at),
                    ),
                    3,
                )
            return out
