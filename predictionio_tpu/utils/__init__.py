"""Shared utilities (profiling, logging helpers)."""

from .profiling import StepTimer, device_trace

__all__ = ["StepTimer", "device_trace"]
