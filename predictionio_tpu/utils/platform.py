"""JAX platform selection that survives environment boot hooks.

The reference's process model launches every train/eval/deploy run as a
child JVM via spark-submit, propagating the parent's configuration
explicitly (``tools/src/main/scala/io/prediction/tools/RunWorkflow.scala:
103-169`` passes ``--env`` and SPARK_YARN_USER_ENV through). The TPU-native
analogue has a sharper failure mode: deployment environments may install a
``sitecustomize`` boot hook that registers an accelerator PJRT plugin in
*every* Python interpreter and pins ``JAX_PLATFORMS`` to it. A child
process that must run on the CPU backend (tests, multi-chip dry-runs on a
virtual device mesh, CI) cannot rely on inheriting the parent's intent —
the hook runs before any user code and may initialize the accelerator
backend eagerly.

This module centralizes the fix:

- :func:`force_cpu_env` — build a child-process environment hard-pinned to
  the CPU backend: sets ``JAX_PLATFORMS=cpu``, strips the accelerator boot
  hook's trigger variables AND its ``PYTHONPATH`` entry (so the hook's
  ``sitecustomize`` is never imported), and optionally forces an N-device
  virtual CPU mesh via ``XLA_FLAGS=--xla_force_host_platform_device_count``.
- :func:`jax_child_env` — environment for spawned workflow/server children:
  if the current process is CPU-pinned (tests), children are CPU-pinned the
  same hard way; otherwise the environment passes through untouched so
  production children reach the real accelerator.
- :func:`force_cpu_in_process` — best-effort in-process CPU pinning for
  code that runs before any JAX backend initialization (mirrors
  ``tests/conftest.py``).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Mapping, Optional

#: Env vars that trigger or configure accelerator boot hooks; removed when a
#: child must come up on the CPU backend. (Prefixes.)
_ACCEL_HOOK_PREFIXES = ("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU")

#: PYTHONPATH entries containing these substrings carry boot-hook
#: ``sitecustomize`` modules and are dropped for CPU children.
_ACCEL_HOOK_PATH_MARKERS = ("axon_site",)

_FORCE_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=\d+")


def _strip_hook_pythonpath(pythonpath: str) -> str:
    parts = [
        p
        for p in pythonpath.split(os.pathsep)
        if p and not any(m in p for m in _ACCEL_HOOK_PATH_MARKERS)
    ]
    return os.pathsep.join(parts)


def force_cpu_env(
    base: Optional[Mapping[str, str]] = None,
    n_devices: Optional[int] = None,
) -> Dict[str, str]:
    """Child-process environment hard-pinned to the JAX CPU backend.

    ``n_devices`` > 1 additionally forces a virtual CPU device mesh
    (the test analogue of the reference's ``local[4]`` Spark master).
    """
    env = dict(base if base is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PIO_JAX_PLATFORM"] = "cpu"
    for key in list(env):
        if key.startswith(_ACCEL_HOOK_PREFIXES):
            del env[key]
    if "PYTHONPATH" in env:
        stripped = _strip_hook_pythonpath(env["PYTHONPATH"])
        if stripped:
            env["PYTHONPATH"] = stripped
        else:
            del env["PYTHONPATH"]
    if n_devices is not None:
        flags = env.get("XLA_FLAGS", "")
        flags = _FORCE_COUNT_RE.sub("", flags).strip()
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    return env


def current_platform() -> str:
    """The platform this process intends: explicit ``PIO_JAX_PLATFORM``
    wins, then ``JAX_PLATFORMS``; empty string means 'let JAX choose'."""
    plat = os.environ.get("PIO_JAX_PLATFORM") or os.environ.get(
        "JAX_PLATFORMS", ""
    )
    return plat.split(",")[0].strip().lower()


def jax_child_env(
    base: Optional[Mapping[str, str]] = None,
    n_devices: Optional[int] = None,
) -> Dict[str, str]:
    """Environment for a spawned workflow/server child process.

    CPU-pinned parents (tests, dry-runs) produce hard-pinned CPU children —
    inheriting ``JAX_PLATFORMS=cpu`` alone is NOT enough when a boot hook
    registers an accelerator plugin eagerly. Anything else passes through
    unchanged so production children reach the real device.
    """
    if current_platform() == "cpu":
        return force_cpu_env(base, n_devices=n_devices)
    return dict(base if base is not None else os.environ)


def force_cpu_in_process() -> None:
    """Pin THIS process to the CPU backend (only reliable before the first
    JAX backend initialization). Mirrors ``tests/conftest.py``."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PIO_JAX_PLATFORM"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # jax missing/already initialized: env pin stands
        pass


def apply_env_platform() -> None:
    """Entry-point hook for driver processes (run_workflow / run_server):
    make the environment's platform choice stick. A boot hook's plugin
    registration can programmatically override ``JAX_PLATFORMS=cpu``;
    re-asserting via ``jax.config.update`` before any backend
    initialization wins (same mechanism as tests/conftest.py)."""
    if current_platform() == "cpu":
        force_cpu_in_process()


def cpu_device_count() -> Optional[int]:
    """Number of visible CPU devices, or ``None`` when the CPU backend is
    unavailable / cannot be queried without initializing an accelerator."""
    try:
        import jax

        return len(jax.devices("cpu"))
    except Exception:
        return None
