"""predictionio_tpu — a TPU-native machine-learning server framework.

A ground-up rebuild of the capabilities of PredictionIO (reference:
``/root/reference``, v0.9.2-SNAPSHOT) designed TPU-first:

- **Storage plane** (:mod:`predictionio_tpu.storage`): append-only event store
  with ``$set/$unset/$delete`` property semantics, metadata DAOs (apps, access
  keys, engine manifests, engine/evaluation instances) and model blob stores.
  (Reference: ``data/src/main/scala/io/prediction/data/storage/``.)
- **Event server** (:mod:`predictionio_tpu.api`): REST ingestion API compatible
  with the reference's ``events.json`` / ``stats.json`` routes.
  (Reference: ``data/src/main/scala/io/prediction/data/api/EventAPI.scala``.)
- **DASE controller** (:mod:`predictionio_tpu.controller`): DataSource →
  Preparator → Algorithm(s) → Serving engine contract, engine-variant JSON
  params, evaluation metrics and memoized hyperparameter sweeps.
  (Reference: ``core/src/main/scala/io/prediction/controller/``.)
- **Workflow runtime** (:mod:`predictionio_tpu.workflow`): train/eval/deploy
  lifecycle with persisted engine instances, a TPU mesh context instead of a
  SparkContext, and a query REST server with hot reload.
  (Reference: ``core/src/main/scala/io/prediction/workflow/``.)
- **Compute plane** (:mod:`predictionio_tpu.ops`, :mod:`predictionio_tpu.models`):
  jit'd / Pallas kernels — blocked ALS with mesh-sharded factor tables, Naive
  Bayes sufficient-statistic reductions, batched gather-dot top-k serving —
  replacing the reference's delegation to Spark MLlib.
- **Parallelism** (:mod:`predictionio_tpu.parallel`): ``jax.sharding.Mesh``
  construction, sharding specs, and collective helpers (ICI within a slice,
  DCN across slices) replacing Spark executor scheduling and shuffles.
"""

__version__ = "0.1.0"
