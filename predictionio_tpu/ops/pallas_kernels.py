"""Pallas TPU kernels for the serving hot path.

The deployed recommender's inner loop is "score every item for a batch of
queries, keep the top k" (reference:
``MatrixFactorizationModel.recommendProducts`` dot-products invoked per query,
``examples/.../ALSAlgorithm.scala:76-86``). The XLA path in
:mod:`predictionio_tpu.ops.scoring` materializes the full ``[B, N]`` score
matrix in HBM before ``top_k``; for large catalogs that write is the
bandwidth bill. This kernel streams item blocks through VMEM instead: each
grid step computes one ``[B, T]`` score tile on the MXU and folds it into a
running ``[B, K]`` top-k kept in VMEM — the ``[B, N]`` matrix never exists.

Exclusion (seen/unavailable items — the e-commerce template's serving-time
filters) is per-query index lists (``[B, E]``, -1 padded), matched against
the block's global item indices, instead of a dense ``[B, N]`` mask.

On non-TPU backends the kernel runs in interpret mode (tests), and
:func:`top_k_streaming` transparently falls back to the XLA path when pallas
is unavailable.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = float("-inf")  # plain scalar: jnp constants cannot be captured by kernels

try:  # pallas is TPU/GPU-oriented; keep the module importable anywhere
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _select_topk(cand_s, cand_i, k: int):
    """Top-k of (scores, indices) along axis 1 by unrolled max-extraction —
    only jnp primitives that lower in Mosaic (no sort/top_k inside kernels).
    """
    b, c = cand_s.shape
    pos_iota = jax.lax.broadcasted_iota(jnp.int32, (b, c), 1)
    out_s, out_i = [], []
    for _ in range(k):
        m = jnp.max(cand_s, axis=1, keepdims=True)  # [B, 1]
        # first position attaining the max
        pos = jnp.min(
            jnp.where(cand_s == m, pos_iota, jnp.int32(c)), axis=1, keepdims=True
        )  # [B, 1]
        sel = pos_iota == pos  # [B, C] one-hot
        idx = jnp.sum(jnp.where(sel, cand_i, 0), axis=1)  # [B]
        out_s.append(m[:, 0])
        out_i.append(idx)
        cand_s = jnp.where(sel, _NEG_INF, cand_s)
    return jnp.stack(out_s, axis=1), jnp.stack(out_i, axis=1)


def _topk_kernel(q_ref, items_ref, excl_ref, out_s_ref, out_i_ref, *,
                 k: int, block_items: int, n_items: int, n_excl: int):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        out_s_ref[:] = jnp.full_like(out_s_ref[:], _NEG_INF)
        out_i_ref[:] = jnp.full_like(out_i_ref[:], -1)

    b = q_ref.shape[0]
    scores = jax.lax.dot_general(
        q_ref[:], items_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, T]
    gidx = j * block_items + jax.lax.broadcasted_iota(
        jnp.int32, (b, block_items), 1
    )
    scores = jnp.where(gidx < n_items, scores, _NEG_INF)
    if n_excl:
        # One excluded id per fori_loop step: the buffer arrives
        # TRANSPOSED as [E, B], so each step reads one sublane row
        # (leading-dim index — always lowerable) and masks with a single
        # 2-D compare. Mosaic rejects lane-dim slices at unaligned
        # offsets and compiles 3-D broadcast compares pathologically
        # slowly (both deviceless-AOT findings), so the earlier
        # [B, T, C]-chunked formulation is gone; total compare work is
        # identical (E × [B, T]).
        def body(e, sc):
            # pio: lint-ok[mosaic-per-row-dma] sequential E-step is by design (ADVICE r5): E ≤ 64 and a [B] sublane row per step is the formulation that lowers; the [B,T,C] chunked compare did not
            ex = excl_ref[e]  # [B]
            hit = gidx == ex[:, None]  # [B, T]
            return jnp.where(hit, _NEG_INF, sc)

        scores = jax.lax.fori_loop(0, n_excl, body, scores)

    cand_s = jnp.concatenate([out_s_ref[:], scores], axis=1)
    cand_i = jnp.concatenate([out_i_ref[:], gidx], axis=1)
    new_s, new_i = _select_topk(cand_s, cand_i, k)
    out_s_ref[:] = new_s
    out_i_ref[:] = new_i


@functools.partial(
    jax.jit,
    static_argnames=("k", "block_items", "n_excl", "interpret"),
)
def _topk_streaming_call(query_vectors, item_factors, exclude_idx, k,
                         block_items, n_excl, interpret):
    b, r = query_vectors.shape
    n_items = item_factors.shape[0]
    n_pad = _round_up(n_items, block_items)
    items = jnp.pad(item_factors, ((0, n_pad - n_items), (0, 0)))
    grid = n_pad // block_items

    kernel = functools.partial(
        _topk_kernel,
        k=k, block_items=block_items, n_items=n_items, n_excl=n_excl,
    )
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((b, r), lambda j: (0, 0)),
            pl.BlockSpec((block_items, r), lambda j: (j, 0)),
            pl.BlockSpec(exclude_idx.shape, lambda j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((b, k), lambda j: (0, 0)),
            pl.BlockSpec((b, k), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ],
        interpret=interpret,
    )(query_vectors, items, exclude_idx)


def top_k_streaming(
    query_vectors: jax.Array,  # [B, R] float32
    item_factors: jax.Array,  # [N, R] float32
    k: int,
    exclude_idx: Optional[jax.Array] = None,  # [B, E] int32, -1 padded
    block_items: int = 1024,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Streaming top-k gather-dot: returns (scores ``[B, k]``, item indices
    ``[B, k]``) without materializing ``[B, N]`` scores in HBM.

    Sentinel contract (all paths — kernel, interpret, XLA fallback): a slot
    with fewer than ``k`` valid candidates (catalog smaller than ``k``, or
    exclusions masking the rest) holds score ``-inf`` and index ``-1``.
    Callers gathering items by index MUST treat ``-1`` as absent — negative
    indexing would otherwise silently map it to the last catalog item.

    ``interpret=None`` auto-selects: compiled on TPU, interpreter elsewhere
    (CPU tests). Queries/rank are padded to VPU/MXU tile boundaries; padding
    never appears in results (-inf / -1 masking).
    """
    if not _HAVE_PALLAS:
        # XLA fallback with the SAME contract: exclusions applied (dense
        # mask), k clamped/padded to the catalog size, -inf slots carry
        # the -1 sentinel. One home for that contract now that the fused
        # serving entries (ops/scoring.py) share it.
        from .scoring import xla_topk_with_sentinels

        return xla_topk_with_sentinels(
            query_vectors, item_factors, k, exclude_idx
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, r = query_vectors.shape
    n_items = item_factors.shape[0]
    k_eff = min(k, n_items)
    b_pad = _round_up(b, 8)
    r_pad = _round_up(r, 128)
    q = jnp.pad(
        jnp.asarray(query_vectors, jnp.float32),
        ((0, b_pad - b), (0, r_pad - r)),
    )
    items = jnp.pad(
        jnp.asarray(item_factors, jnp.float32), ((0, 0), (0, r_pad - r))
    )
    if exclude_idx is None or exclude_idx.shape[1] == 0:
        # n_excl=0 → the kernel skips exclusion entirely (the 1-row filler
        # only exists because pallas inputs need a nonzero dim)
        excl = jnp.full((1, b_pad), -1, dtype=jnp.int32)
        n_excl = 0
    else:
        e = exclude_idx.shape[1]
        # transpose to [E, B]: the kernel reads one exclusion row per
        # loop step via a leading-dim index (see _topk_kernel)
        excl = jnp.pad(
            jnp.asarray(exclude_idx, jnp.int32),
            ((0, b_pad - b), (0, 0)),
            constant_values=-1,
        ).T
        n_excl = e

    block = min(block_items, _round_up(n_items, 128))
    scores, idx = _topk_streaming_call(
        q, items, excl, k_eff, block, n_excl, interpret
    )
    scores, idx = scores[:b], idx[:b]
    if k_eff < k:
        pad = k - k_eff
        scores = jnp.pad(scores, ((0, 0), (0, pad)), constant_values=-np.inf)
        idx = jnp.pad(idx, ((0, 0), (0, pad)), constant_values=-1)
    return scores, idx


# ---------------------------------------------------------------------------
# Batched SPD solve (the ALS normal-equation hot op)
# ---------------------------------------------------------------------------
#
# XLA's batched Cholesky lowering runs at ~10 GFLOP/s on TPU for the [B, 50,
# 50] systems ALS produces (measured: ~6.7 µs per matrix — it was ~2/3 of the
# ALS iteration). This kernel fuses factorization + both triangular solves
# into one VMEM-resident pass in a transposed [n, n, B] layout: the batch
# rides the 128-wide lane dimension (full vector-register utilization), and
# extracting column j of every matrix is a cheap dim-0 slice instead of a
# masked reduction. Measured marginal cost ~0.24 µs per matrix (~25×).
#
# Algorithm (right-looking Cholesky, one fused FMA pass per step):
#   step j: colj = a[j]            (trailing block is symmetric)
#           lj   = colj / sqrt(a[j,j])
#           a   -= (lj - e_j) ⊗ lj (trailing update + stores L's column j
#                                    into row j of `a`, which the update has
#                                    just zeroed)
# Forward substitution interleaves with factorization (z_j available as soon
# as column j is); back substitution replays the stored rows in reverse.
# Zero-padding (rank → n multiple of 8, and all-zero padding matrices from
# bucket padding) flows through inv_d = where(d>0, 1/d, 0): padded outputs
# are exactly 0, no NaNs.

#: lane-block of matrices per grid step; VMEM scratch is n*n*blk*4 bytes.
_SPD_BLK = 128


def _spd_kernel(a_ref, b_ref, x_ref, a_s, y_s, *, n: int):
    a_s[...] = a_ref[...]
    y_s[...] = b_ref[...]
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)

    def fwd(j, _):
        colj = a_s[j]  # [n, blk] — column j of the trailing block
        ej = (row_iota == j).astype(jnp.float32)  # [n, 1]
        d2 = jnp.sum(colj * ej, axis=0)  # [blk] — diagonal entry
        inv_d = jnp.where(d2 > 0, jax.lax.rsqrt(d2), 0.0)
        lj = colj * inv_d[None, :]  # column j of L (diag value at row j)
        ljm = lj - ej  # (d - 1) at row j → the update stores lj into row j
        a_s[...] = a_s[...] - ljm[:, None, :] * lj[None, :, :]
        zj = jnp.sum(y_s[...] * ej, axis=0) * inv_d  # [blk]
        y_s[...] = y_s[...] - ljm * zj[None, :]
        return 0

    jax.lax.fori_loop(0, n, fwd, 0)
    x_ref[...] = jnp.zeros_like(x_ref)

    def bwd(jj, _):
        j = n - 1 - jj
        lrow = a_s[j]  # row j now holds L[:, j]
        ej = (row_iota == j).astype(jnp.float32)
        d = jnp.sum(lrow * ej, axis=0)
        inv_d = jnp.where(d > 0, 1.0 / d, 0.0)
        dot = jnp.sum(lrow * x_ref[...], axis=0)  # x[j] still 0 here
        zj = jnp.sum(y_s[...] * ej, axis=0)
        x_ref[...] = x_ref[...] + ej * ((zj - dot) * inv_d)[None, :]
        return 0

    jax.lax.fori_loop(0, n, bwd, 0)


def spd_solve_t(
    a_t: jax.Array,  # [n, n, B] float32 — SPD systems, batch on lanes
    b_t: jax.Array,  # [n, B] float32
    interpret: Optional[bool] = None,
) -> jax.Array:  # [n, B] float32
    """Fused batched Cholesky solve in transposed layout.

    Requires ``n % 8 == 0`` and ``B % 128 == 0`` (callers pad; zero-padding
    solves to exactly 0). Falls back to ``cho_solve`` when pallas is
    unavailable. ``interpret=None`` auto-selects interpreter off-TPU.
    """
    n, n2, bsz = a_t.shape
    if n != n2 or n % 8 != 0 or bsz % _SPD_BLK != 0:
        raise ValueError(f"spd_solve_t: bad shapes {a_t.shape}")
    if not _HAVE_PALLAS:
        a = jnp.moveaxis(a_t, -1, 0)  # [B, n, n]
        # zero-padding guard: cho_factor of a zero matrix NaNs, so ridge
        # the padded systems with I and zero their solutions afterwards —
        # the kernel contract is "all-zero system ⇒ exactly-zero x"
        # regardless of the rhs.
        zero = jnp.trace(a, axis1=-2, axis2=-1) == 0
        a = a + zero[:, None, None] * jnp.eye(n, dtype=a.dtype)
        chol = jax.scipy.linalg.cho_factor(a, lower=True)
        x = jax.scipy.linalg.cho_solve(chol, b_t.T)
        return jnp.where(zero[None, :], 0.0, x.T)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return pl.pallas_call(
        functools.partial(_spd_kernel, n=n),
        grid=(bsz // _SPD_BLK,),
        in_specs=[
            pl.BlockSpec((n, n, _SPD_BLK), lambda i: (0, 0, i)),
            pl.BlockSpec((n, _SPD_BLK), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((n, _SPD_BLK), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((n, bsz), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((n, n, _SPD_BLK), jnp.float32),
            pltpu.VMEM((n, _SPD_BLK), jnp.float32),
        ],
        interpret=interpret,
    )(a_t, b_t)


# ---------------------------------------------------------------------------
# Fused gather + Gramian (the ALS normal-equation build)
# ---------------------------------------------------------------------------
#
# The XLA path materializes the gathered factors ``g = y[idx] * mask`` as a
# [B, K, R] tensor in HBM and the Gramian einsum re-reads it — the gathered
# bytes are paid ~3× (write + read + the original gather read). Measured
# consequence (PERF.md, round 3): the ALS iteration is gather-bound at
# ~0.32 of v5e HBM peak. This kernel streams each factor row HBM→VMEM
# exactly once: per solve row, per K-tile, it issues one async copy per
# rating's factor row into a VMEM tile, accumulates ``A += (g·w)ᵀ g`` and
# ``b += gᵀ rhs`` in f32 on the MXU, and writes each row's [R, R] system
# once. The [B, K, R] intermediate never exists.
#
# Cost model (why this can win despite per-row DMAs): the XLA path moves
# ~3 × B·K·R·4 bytes of HBM traffic per chunk; this kernel moves
# B·K·(R·4 + ~overhead) with K_tile copies in flight to hide latency. The
# risk is DMA-issue rate on small (rank·4 ≈ 200 B) transfers. Since
# round 12 the kernel is the DEFAULT build wherever the pallas solver
# resolves (ALSConfig.fused_gather=None; BENCH_FUSED_GATHER=0 /
# fused_gather=False opt out) — the issue-rate question is still open
# on silicon and sits FIRST on the hardware-day bisect checklist
# (docs/hardware_day.md "Reclaiming the 3.29×").
#
# Replaces the same MLlib hot loop as the solver above (reference:
# ``examples/scala-parallel-recommendation/custom-prepartor/src/main/
# scala/ALSAlgorithm.scala:56-62``; SURVEY §2.8 "per-block normal
# equations").

#: Max factor rows (DMAs) in flight per K-tile; VMEM tile is kt·r_pad·4 B.
_FUSED_K_TILE = 512
#: Max solve rows per grid step — bounds the [Bt, R, R] output block and
#: the [Bt, K] index block in SMEM (Bt·K ≤ _FUSED_SMEM_IDX ints).
_FUSED_B_TILE = 128
_FUSED_SMEM_IDX = 32768
#: Widest K a single kernel call takes. Wider problems (the rare
#: ultra-high-degree buckets) are split into K-slices summed in XLA.
#: The per-call SMEM index block is [bt, k] with bt·k ≤ _FUSED_SMEM_IDX,
#: so the real scalar-memory bound is _FUSED_SMEM_IDX·4 B = 128 KB
#: regardless of this constant; the split's job is to keep a SINGLE
#: row's index list (bt can't go below 1) within that same bound.
_FUSED_K_SPLIT = 8192


def _gramian_kernel(idx_ref, w2_ref, rhs_ref, ridge_ref, y_ref, yty_ref,
                    a_ref, b_ref, gbuf, sem, *, k_tiles, kt, bt, r):
    """Double-buffered over (row, K-tile) steps: while tile s's [kt, r]
    gather block is being multiplied, tile s+1's row copies are already
    in flight into the other VMEM slot — DMA latency hides behind MXU
    work instead of serializing with it. One DMA semaphore per slot: a
    shared semaphore would mix completions of in-flight tiles and could
    release a wait with the other tile's copies."""
    eye = (
        jax.lax.broadcasted_iota(jnp.int32, (r, r), 0)
        == jax.lax.broadcasted_iota(jnp.int32, (r, r), 1)
    ).astype(jnp.float32)
    total = bt * k_tiles

    def copies(s, slot, action):
        """Start or wait the kt row copies of flat tile s in `slot`
        (wait recreates the same descriptors; each wait consumes one
        copy's worth of the slot's semaphore)."""
        b = s // k_tiles
        t = s % k_tiles

        def one(k, _):
            # pio: lint-ok[mosaic-per-row-dma] the per-row gather IS this kernel's design; default-ON with the pallas solver since round 12 (explicit opt-out BENCH_FUSED_GATHER=0 / fused_gather=False), with the DMA-issue rate still first on the hardware-day A/B bisect list (docs/hardware_day.md)
            dma = pltpu.make_async_copy(
                y_ref.at[pl.ds(idx_ref[b, t * kt + k], 1), :],
                gbuf.at[slot, pl.ds(k, 1), :],
                sem.at[slot],
            )
            (dma.start if action == "start" else dma.wait)()
            return 0

        jax.lax.fori_loop(0, kt, one, 0)

    copies(0, 0, "start")

    def body(s, carry):
        a_acc, b_acc = carry
        slot = s % 2
        b = s // k_tiles
        t = s % k_tiles

        @pl.when(s + 1 < total)
        def _():
            copies(s + 1, (s + 1) % 2, "start")

        copies(s, slot, "wait")
        g = gbuf[slot]  # [kt, r] f32 (bf16 tables upcast at kernel entry)
        # reshape [kt] -> [kt, 1] in f32, THEN cast: Mosaic's layout
        # inference rejects the 1-D->2-D shape cast on bf16 vectors
        # (found by deviceless AOT compile of the bf16-gather variant)
        # pio: lint-ok[mosaic-unaligned-lane-slice] kt is a static param the AST cannot resolve; the wrapper guarantees kt % 128 == 0 (rounded at the gramian_fused entry), so t*kt offsets and kt sizes are lane-aligned
        w = w2_ref[b, pl.ds(t * kt, kt)][:, None].astype(g.dtype)
        # pio: lint-ok[mosaic-unaligned-lane-slice] same kt %128 wrapper guarantee as the w2 slice above
        rr = rhs_ref[b, pl.ds(t * kt, kt)][:, None].astype(g.dtype)
        a_acc = a_acc + jax.lax.dot_general(
            g * w, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        b_acc = b_acc + jnp.sum(
            (g * rr).astype(jnp.float32), axis=0
        )

        is_last_tile = t == k_tiles - 1

        @pl.when(is_last_tile)
        def _():
            a_ref[b] = a_acc + yty_ref[...] + ridge_ref[b] * eye
            b_ref[b] = b_acc

        # reset the accumulators at each row boundary — a select, not a
        # multiply: 0 * Inf = NaN would leak one bad row's overflow into
        # every subsequent row of the tile
        return (
            jnp.where(is_last_tile, jnp.zeros_like(a_acc), a_acc),
            jnp.where(is_last_tile, jnp.zeros_like(b_acc), b_acc),
        )

    jax.lax.fori_loop(
        0, total, body,
        (jnp.zeros((r, r), jnp.float32), jnp.zeros((r,), jnp.float32)),
    )


@functools.partial(
    jax.jit, static_argnames=("bt", "kt", "interpret")
)
def _gramian_fused_call(y, idx, w2, rhs, ridge, yty, bt, kt, interpret):
    b, k = idx.shape
    r = y.shape[1]
    return pl.pallas_call(
        functools.partial(
            _gramian_kernel, k_tiles=k // kt, kt=kt, bt=bt, r=r
        ),
        grid=(b // bt,),
        in_specs=[
            pl.BlockSpec((bt, k), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((bt, k), lambda i: (i, 0)),
            pl.BlockSpec((bt, k), lambda i: (i, 0)),
            pl.BlockSpec((bt,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # y stays in HBM
            pl.BlockSpec((r, r), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bt, r, r), lambda i: (i, 0, 0)),
            pl.BlockSpec((bt, r), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, r, r), jnp.float32),
            jax.ShapeDtypeStruct((b, r), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, kt, r), y.dtype),  # double-buffered gather tile
            pltpu.SemaphoreType.DMA((2,)),  # one per slot
        ],
        interpret=interpret,
    )(idx, w2, rhs, ridge, y, yty)


def gramian_fused(
    y: jax.Array,  # [N, R] f32 or bf16 — opposite-side factor table (HBM)
    idx: jax.Array,  # [B, K] int32 — factor-row index per rating (0-padded)
    w2: jax.Array,  # [B, K] f32 — Gramian weight (mask, or c-1 implicit)
    rhs: jax.Array,  # [B, K] f32 — rhs weight (masked rating / c·p)
    ridge: jax.Array,  # [B] f32 — per-row diagonal ridge (λ·n_u)
    yty: Optional[jax.Array] = None,  # [R, R] f32 — implicit-mode base
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused normal-equation build: returns ``(A [B, R, R] f32, b [B, R]
    f32)`` with ``A_b = yty + ridge_b·I + Σ_k w2[b,k]·y[idx[b,k]]⊗y[idx[b,k]]``
    and ``b_b = Σ_k rhs[b,k]·y[idx[b,k]]`` — without materializing the
    ``[B, K, R]`` gathered-factor intermediate in HBM.

    Padding contract: invalid (b, k) slots must carry ``w2 = rhs = 0``
    (their ``idx`` may be any in-range value; 0 by convention) — the
    gathered row is multiplied by zero, so correctness never depends on
    the index padding. ``R`` must be a multiple of 8 (callers pad the rank
    once, as the solver path already does); B and K are padded here, and R
    is lane-padded to 128 internally: Mosaic requires DMA slices to be
    aligned to the 128-lane tiling (discovered by deviceless AOT compile —
    a 1×56 row copy does not lower), so the kernel streams aligned 1×128
    rows of a zero-padded table instead. The padded lanes contribute
    zeros to A and b, and a 56-wide Gramian already occupies one 128×128
    MXU tile, so the extra lanes cost DMA bytes only: r_pad·4 = 512 B per
    row vs the XLA path's ~3·r·4 = 672 B at bench rank — a thinner win
    than the unpadded 224 B, which is what the hardware A/B prices.

    ``interpret=None`` auto-selects interpreter off-TPU. No XLA fallback:
    the caller (``_solve_side_traced``) owns the dispatch — default-ON
    with the pallas solver since round 12, with ``fused_gather=False``
    as the explicit einsum-build opt-out and narrow (K < rank) buckets
    auto-kept on the einsum path.
    """
    if not _HAVE_PALLAS:
        raise NotImplementedError(
            "gramian_fused requires pallas; use the einsum path"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, r = y.shape
    if r % 8 != 0:
        raise ValueError(f"gramian_fused: rank must be padded to 8s, got {r}")
    b, k = idx.shape
    if k > _FUSED_K_SPLIT:
        # K-slice split: base terms (ridge·I, yty) ride the first slice
        # only, the rest contribute pure Σ w·y⊗y — summing slice outputs
        # is exact. Costs one [B, R, R] add per extra slice, paid only by
        # the ultra-wide buckets.
        a_tot, b_tot = None, None
        zero_ridge = jnp.zeros_like(jnp.asarray(ridge, jnp.float32))
        for k0 in range(0, k, _FUSED_K_SPLIT):
            sl = slice(k0, min(k, k0 + _FUSED_K_SPLIT))
            a_s, b_s = gramian_fused(
                y, idx[:, sl], w2[:, sl], rhs[:, sl],
                ridge if k0 == 0 else zero_ridge,
                yty if k0 == 0 else None,
                interpret=interpret,
            )
            a_tot = a_s if a_tot is None else a_tot + a_s
            b_tot = b_s if b_tot is None else b_tot + b_s
        return a_tot, b_tot
    # kt must be lane-aligned: the kernel slices w2/rhs at [b, t*kt : +kt]
    # in the lane dim, and Mosaic rejects unaligned lane slices (the same
    # deviceless-AOT finding as the 1×56 row DMAs). The AOT sweep only
    # covers k ≥ 512 shapes where kt == _FUSED_K_TILE; rounding keeps the
    # guarantee for narrow buckets too (padding contract absorbs the
    # zero-weighted extra slots).
    kt = min(_round_up(k, 128), _FUSED_K_TILE)
    k_pad = _round_up(k, kt)
    bt = min(_FUSED_B_TILE, max(1, _FUSED_SMEM_IDX // k_pad))
    b_pad = _round_up(b, bt)
    idx = jnp.asarray(idx, jnp.int32)
    w2 = jnp.asarray(w2, jnp.float32)
    rhs = jnp.asarray(rhs, jnp.float32)
    if k_pad != k or b_pad != b:
        pk, pb = k_pad - k, b_pad - b
        idx = jnp.pad(idx, ((0, pb), (0, pk)))
        w2 = jnp.pad(w2, ((0, pb), (0, pk)))
        rhs = jnp.pad(rhs, ((0, pb), (0, pk)))
        ridge = jnp.pad(jnp.asarray(ridge, jnp.float32), (0, pb))
    if y.dtype == jnp.bfloat16:
        # Per-row DMA floor (deviceless-AOT finding): Mosaic cannot slice
        # one sublane of a bf16-tiled VMEM buffer, and the minimum
        # lane-aligned copy is 128 lanes × 32 bits = 512 B — so bf16
        # CANNOT reduce this kernel's gathered bytes below the f32 path's
        # 512 B/row. Upcasting is exact and keeps BENCH_GATHER_DTYPE=bf16
        # composable with BENCH_FUSED_GATHER=1 (the combined leg then
        # measures the fused kernel at f32 table width, honestly).
        y = y.astype(jnp.float32)
    # lane-pad the factor table so every per-row DMA is a tiling-aligned
    # 1×r_pad copy (see docstring); the zero lanes are inert in A and b
    r_pad = _round_up(r, 128)
    if r_pad != r:
        y = jnp.pad(y, ((0, 0), (0, r_pad - r)))
    if yty is None:
        yty = jnp.zeros((r_pad, r_pad), jnp.float32)
    elif r_pad != r:
        yty = jnp.pad(jnp.asarray(yty, jnp.float32),
                      ((0, r_pad - r), (0, r_pad - r)))
    a, bvec = _gramian_fused_call(
        y, idx, w2, rhs, jnp.asarray(ridge, jnp.float32), yty,
        bt, kt, interpret,
    )
    return a[:b, :r, :r], bvec[:b, :r]


def top_k_for_users_streaming(
    user_factors: jax.Array,
    item_factors: jax.Array,
    user_idx: jax.Array,
    k: int,
    exclude_idx: Optional[jax.Array] = None,
    **kw,
) -> Tuple[jax.Array, jax.Array]:
    """Known-user wrapper (gather user vectors, then stream)."""
    return top_k_streaming(
        user_factors[user_idx], item_factors, k, exclude_idx, **kw
    )
