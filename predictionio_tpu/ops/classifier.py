"""Multinomial Naive Bayes on TPU (MLlib semantics).

The classification template delegates to ``NaiveBayes.train(points, lambda)``
(``examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:19-27``). MLlib's multinomial NB over numeric
feature vectors, with additive (Laplace) smoothing ``lambda``:

    pi_c      = log((N_c + λ) / (N + λ·C))
    theta_c,j = log((Σ_{i∈c} x_ij + λ) / (Σ_{i∈c} Σ_j x_ij + λ·D))
    predict x = argmax_c  pi_c + theta_c · x

The per-class sufficient statistics (counts and feature sums) are
scatter-adds over the label index — on a data-sharded mesh they reduce with
a single ``psum`` instead of MLlib's ``combineByKey`` shuffle (SURVEY §2.8).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class MultinomialNBModel:
    """``NaiveBayesModel(labels, pi, theta)`` analogue.

    ``class_values`` holds the original label values (MLlib labels are
    doubles, e.g. the "plan" property); row ``c`` of ``pi``/``theta``
    corresponds to ``class_values[c]``.
    """

    class_values: np.ndarray  # [C] original label values
    pi: np.ndarray  # [C] log priors
    theta: np.ndarray  # [C, D] log feature likelihoods

    def predict(self, features: Sequence[float]) -> float:
        return float(self.predict_batch(np.asarray(features)[None])[0])

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        """[N, D] → [N] predicted label values (one device matmul)."""
        scores = _score(
            jnp.asarray(features, jnp.float32),
            jnp.asarray(self.pi),
            jnp.asarray(self.theta),
        )
        return self.class_values[np.asarray(scores)]

    def sanity_check(self) -> None:
        if not np.isfinite(self.pi).all() or not np.isfinite(self.theta).all():
            raise ValueError("MultinomialNBModel has non-finite parameters")


@jax.jit
def _score(x, pi, theta):
    # scores[n, c] = pi[c] + theta[c, :] @ x[n, :]  — MXU matmul
    return jnp.argmax(
        pi[None, :] + x @ theta.T, axis=1
    )


def train(
    features: np.ndarray,  # [N, D] non-negative feature values
    labels: np.ndarray,  # [N] label values (any dtype; distinct values = classes)
    lam: float = 1.0,
) -> MultinomialNBModel:
    """``NaiveBayes.train`` (MLlib ``NaiveBayes.scala`` run method) with
    additive smoothing."""
    features = np.asarray(features, np.float32)
    labels = np.asarray(labels)
    if features.ndim != 2 or features.shape[0] != labels.shape[0]:
        raise ValueError(
            f"features {features.shape} and labels {labels.shape} mismatch"
        )
    if features.shape[0] == 0:
        raise ValueError("Cannot train NaiveBayes on an empty dataset")
    if (features < 0).any():
        raise ValueError(
            "Multinomial NaiveBayes requires non-negative feature values"
        )
    class_values, label_idx = np.unique(labels, return_inverse=True)
    n_classes = class_values.shape[0]
    n, d = features.shape

    @jax.jit
    def stats(x, li):
        counts = jnp.zeros((n_classes,), jnp.float32).at[li].add(1.0)
        sums = jnp.zeros((n_classes, d), jnp.float32).at[li].add(x)
        return counts, sums

    counts, sums = stats(jnp.asarray(features), jnp.asarray(label_idx, jnp.int32))
    counts = np.asarray(counts, np.float64)
    sums = np.asarray(sums, np.float64)

    pi = np.log(counts + lam) - np.log(n + lam * n_classes)
    theta = np.log(sums + lam) - np.log(
        sums.sum(axis=1, keepdims=True) + lam * d
    )
    return MultinomialNBModel(
        class_values=class_values, pi=pi, theta=theta
    )
