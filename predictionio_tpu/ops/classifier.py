"""Multinomial Naive Bayes on TPU (MLlib semantics).

The classification template delegates to ``NaiveBayes.train(points, lambda)``
(``examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:19-27``). MLlib's multinomial NB over numeric
feature vectors, with additive (Laplace) smoothing ``lambda``:

    pi_c      = log((N_c + λ) / (N + λ·C))
    theta_c,j = log((Σ_{i∈c} x_ij + λ) / (Σ_{i∈c} Σ_j x_ij + λ·D))
    predict x = argmax_c  pi_c + theta_c · x

The per-class sufficient statistics (counts and feature sums) are
scatter-adds over the label index — on a data-sharded mesh they reduce with
a single ``psum`` instead of MLlib's ``combineByKey`` shuffle (SURVEY §2.8).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class MultinomialNBModel:
    """``NaiveBayesModel(labels, pi, theta)`` analogue.

    ``class_values`` holds the original label values (MLlib labels are
    doubles, e.g. the "plan" property); row ``c`` of ``pi``/``theta``
    corresponds to ``class_values[c]``.

    ``counts``/``sums`` are the per-class sufficient statistics the
    parameters were derived from. Because multinomial NB's statistics
    are ADDITIVE over examples, keeping them makes :func:`fold_in`
    exact: folding new examples produces bit-for-bit the model a full
    retrain on the union would — the property the continuous
    controller's fold path leans on (docs/continuous.md). ``None`` on a
    model deserialized from before they existed; fold_in refuses those.
    """

    class_values: np.ndarray  # [C] original label values
    pi: np.ndarray  # [C] log priors
    theta: np.ndarray  # [C, D] log feature likelihoods
    counts: np.ndarray = None  # [C] per-class example counts
    sums: np.ndarray = None  # [C, D] per-class feature sums
    lam: float = 1.0  # the smoothing the parameters were built with

    def predict(self, features: Sequence[float]) -> float:
        return float(self.predict_batch(np.asarray(features)[None])[0])

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        """[N, D] → [N] predicted label values (one device matmul)."""
        scores = _score(
            jnp.asarray(features, jnp.float32),
            jnp.asarray(self.pi),
            jnp.asarray(self.theta),
        )
        return self.class_values[np.asarray(scores)]

    def sanity_check(self) -> None:
        if not np.isfinite(self.pi).all() or not np.isfinite(self.theta).all():
            raise ValueError("MultinomialNBModel has non-finite parameters")


@jax.jit
def _score(x, pi, theta):
    # scores[n, c] = pi[c] + theta[c, :] @ x[n, :]  — MXU matmul
    return jnp.argmax(
        pi[None, :] + x @ theta.T, axis=1
    )


def train(
    features: np.ndarray,  # [N, D] non-negative feature values
    labels: np.ndarray,  # [N] label values (any dtype; distinct values = classes)
    lam: float = 1.0,
) -> MultinomialNBModel:
    """``NaiveBayes.train`` (MLlib ``NaiveBayes.scala`` run method) with
    additive smoothing."""
    features = np.asarray(features, np.float32)
    labels = np.asarray(labels)
    if features.ndim != 2 or features.shape[0] != labels.shape[0]:
        raise ValueError(
            f"features {features.shape} and labels {labels.shape} mismatch"
        )
    if features.shape[0] == 0:
        raise ValueError("Cannot train NaiveBayes on an empty dataset")
    if (features < 0).any():
        raise ValueError(
            "Multinomial NaiveBayes requires non-negative feature values"
        )
    class_values, label_idx = np.unique(labels, return_inverse=True)
    n_classes = class_values.shape[0]
    n, d = features.shape

    @jax.jit
    def stats(x, li):
        counts = jnp.zeros((n_classes,), jnp.float32).at[li].add(1.0)
        sums = jnp.zeros((n_classes, d), jnp.float32).at[li].add(x)
        return counts, sums

    counts, sums = stats(jnp.asarray(features), jnp.asarray(label_idx, jnp.int32))
    counts = np.asarray(counts, np.float64)
    sums = np.asarray(sums, np.float64)

    return _from_stats(class_values, counts, sums, lam)


def _from_stats(
    class_values: np.ndarray,
    counts: np.ndarray,  # [C] float64
    sums: np.ndarray,  # [C, D] float64
    lam: float,
) -> MultinomialNBModel:
    """Derive (pi, theta) from sufficient statistics — the single place
    the smoothing formulas live, so train and fold can't drift apart."""
    n = counts.sum()
    n_classes, d = sums.shape
    pi = np.log(counts + lam) - np.log(n + lam * n_classes)
    theta = np.log(sums + lam) - np.log(
        sums.sum(axis=1, keepdims=True) + lam * d
    )
    return MultinomialNBModel(
        class_values=class_values,
        pi=pi,
        theta=theta,
        counts=counts,
        sums=sums,
        lam=lam,
    )


def fold_in(
    model: MultinomialNBModel,
    features: np.ndarray,  # [M, D] new examples' feature values
    labels: np.ndarray,  # [M] new examples' label values
) -> MultinomialNBModel:
    """Fold new labelled examples into a trained model without a retrain.

    Adds the examples' scatter-add statistics to the model's retained
    ``counts``/``sums`` and re-derives (pi, theta) with the same
    smoothing — for examples not in the original training set this is
    EXACT: identical to retraining on the union. Unseen label values
    extend the class axis (a zero-stat row plus the new examples).

    Re-folding an entity whose properties changed is approximate (its
    old contribution is still in the statistics); the caller measures
    that drift against its fold policy.
    """
    if model.counts is None or model.sums is None:
        raise ValueError(
            "model has no sufficient statistics (trained before they were "
            "retained?) — fold_in needs counts/sums; retrain instead"
        )
    features = np.asarray(features, np.float64)
    labels = np.asarray(labels)
    if features.ndim != 2 or features.shape[0] != labels.shape[0]:
        raise ValueError(
            f"features {features.shape} and labels {labels.shape} mismatch"
        )
    if features.shape[1] != model.sums.shape[1]:
        raise ValueError(
            f"feature dimension {features.shape[1]} != model's "
            f"{model.sums.shape[1]}"
        )
    if (features < 0).any():
        raise ValueError(
            "Multinomial NaiveBayes requires non-negative feature values"
        )
    class_values = model.class_values
    counts = np.array(model.counts, np.float64)
    sums = np.array(model.sums, np.float64)
    fresh = np.setdiff1d(np.unique(labels), class_values)
    if fresh.size:
        class_values = np.concatenate([class_values, fresh])
        order = np.argsort(class_values, kind="stable")
        class_values = class_values[order]
        grown_counts = np.concatenate([counts, np.zeros(fresh.size)])
        grown_sums = np.concatenate(
            [sums, np.zeros((fresh.size, sums.shape[1]))]
        )
        counts, sums = grown_counts[order], grown_sums[order]
    # M is a delta batch (small); plain numpy scatter-add beats a jit
    row = np.searchsorted(class_values, labels)
    np.add.at(counts, row, 1.0)
    np.add.at(sums, row, features)
    return _from_stats(class_values, counts, sums, model.lam)
