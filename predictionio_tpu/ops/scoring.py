"""Serving-side scoring kernels.

The hot path of the deployed recommendation engine: the reference scores via
``MatrixFactorizationModel.recommendProducts`` (factor dot products, invoked
per query in ``examples/.../ALSAlgorithm.scala:76-80``); here queries are
batched into one gather → matmul → top-k device call
(SURVEY §3.2 "batched gather-dot kernel").

All kernels are jit'd with static k so repeated serving calls hit the
compilation cache; the query batch rides the mesh ``data`` axis when the
server shards a batch across chips.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..quant.ragged import ragged_gather

# plain float, NOT jnp.float32(...): a module-level jnp scalar would
# initialize the device backend at import time (slow start-up for every
# CLI invocation, and a hang if the accelerator is unreachable)
NEG_INF = float("-inf")


#: "auto" streams on TPU once the would-be ``[B, I]`` score matrix
#: exceeds this many bytes (64 MB). Below it the XLA dense path wins:
#: the matrix fits comfortably and the streaming kernel's unrolled
#: k-pass extraction costs k sweeps per tile. Above it the dense path's
#: HBM write+read of the score matrix is the serving bandwidth bill the
#: fused kernel removes — the round-12 default-flip lowered the bar
#: from 1 GB ("only when mandatory") to this ("whenever it wins").
STREAMING_TOPK_BYTES = 1 << 26


def use_streaming_topk(mode: str, b_pad: int, n_items: int) -> bool:
    """Shared streaming-top-k selection rule for serving templates.

    Streaming (``pallas_kernels.top_k_streaming``) keeps the ``[B, I]``
    score matrix out of HBM entirely. "auto" switches at
    :data:`STREAMING_TOPK_BYTES` of would-be scores on TPU (the XLA
    dense path is faster below that and the interpret-mode kernel is
    slow off-TPU, where the fused entry points fall back to XLA
    ``lax.top_k``). Raises on an unknown mode so a config typo fails at
    validation time, not mid-serving.
    """
    if mode not in ("auto", "always", "never"):
        raise ValueError(
            f"streaming_top_k must be 'auto', 'always' or 'never', "
            f"got {mode!r}"
        )
    if mode == "never":
        return False
    if mode == "always":
        return True
    import jax

    return (
        jax.default_backend() == "tpu"
        and b_pad * n_items * 4 > STREAMING_TOPK_BYTES
    )


def pad_pow2(n: int, lo: int = 1) -> int:
    """Smallest power of two >= max(n, lo).

    Serving shape-bucketing: micro-batched query batches arrive at every
    size from 1 to batch_max; dispatching each size directly would compile
    a fresh XLA program per size (20-40 s each on TPU). Padding batch and
    k to powers of two bounds the compile set to O(log) shapes."""
    n = max(int(n), lo)
    return 1 << (n - 1).bit_length()


def _score_topk(query_vectors, item_factors, k, exclude_mask):
    scores = jnp.einsum(
        "br,ir->bi", query_vectors, item_factors, preferred_element_type=jnp.float32
    )
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, NEG_INF, scores)
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_for_users(
    user_factors: jax.Array,  # [U, R]
    item_factors: jax.Array,  # [I, R]
    user_idx: jax.Array,  # [B] int32
    k: int,
    exclude_mask: Optional[jax.Array] = None,  # [B, I] bool — True = exclude
) -> Tuple[jax.Array, jax.Array]:
    """Top-k items for a batch of known users.

    Returns (scores [B, k], item indices [B, k]). ``exclude_mask`` implements
    the seen/unavailable-item filters the e-commerce template applies
    (reference ``ALSAlgorithm.scala`` in ecommerce template).
    """
    return _score_topk(user_factors[user_idx], item_factors, k, exclude_mask)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_for_vectors(
    query_vectors: jax.Array,  # [B, R]
    item_factors: jax.Array,  # [I, R]
    k: int,
    exclude_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k items for raw query vectors (cold-start / feature queries)."""
    return _score_topk(query_vectors, item_factors, k, exclude_mask)


@functools.partial(jax.jit, static_argnames=("k", "exclude_self"))
def top_k_similar_items(
    item_factors: jax.Array,  # [I, R]
    item_idx: jax.Array,  # [B] int32
    k: int,
    exclude_self: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Cosine-similar items — the similarproduct template's kernel
    (reference: ALS ``productFeatures`` cosine,
    ``examples/scala-parallel-similarproduct``).

    Returns (cosine scores [B, k], item indices [B, k]); when
    ``exclude_self`` the query item's own score is masked to -inf before
    the top-k selection.
    """
    norms = jnp.linalg.norm(item_factors, axis=1, keepdims=True)
    unit = item_factors / jnp.maximum(norms, 1e-12)
    q = unit[item_idx]  # [B, R]
    scores = jnp.einsum("br,ir->bi", q, unit, preferred_element_type=jnp.float32)
    if exclude_self:
        n_items = item_factors.shape[0]
        one_hot = jax.nn.one_hot(item_idx, n_items, dtype=jnp.bool_)
        scores = jnp.where(one_hot, NEG_INF, scores)
    return jax.lax.top_k(scores, k)


# -- fused score+select top-k (docs/performance.md#levers) ------------------
#
# One serving entry point per query kind that never materializes the
# [B, I] score matrix when the backend can avoid it: on TPU (when
# use_streaming_topk says streaming wins) the Pallas streaming kernel
# folds each item tile's scores into a VMEM-resident running top-k; off
# TPU (or below the streaming bar) an XLA score + lax.top_k fallback
# with the SAME result contract. Both paths keep the factor tables
# device-resident and return only [B, k] to the host. Exactness vs the
# dense kernels is pinned in tests/test_als.py::TestFusedTopK — same
# items, same order, scores to f32 reassociation tolerance (the
# fleet/merge.py merged_matches_reference contract).
#
# Sentinel contract (inherited from top_k_streaming, BOTH paths): a slot
# with fewer than k valid candidates holds score -inf and index -1 —
# callers must treat -1 as absent, never index with it.


def xla_topk_with_sentinels(
    query_vectors: jax.Array,
    item_factors: jax.Array,
    k: int,
    exclude_idx: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """The XLA fallback leg of the fused top-k: dense score + ``lax.top_k``
    normalized to the streaming kernel's sentinel contract (-inf / -1 on
    invalid slots, k padded past the catalog size). Index-list exclusions
    (``[B, E]`` int32, -1 padded) densify to a one-hot mask here — the
    dense path pays the [B, I] bytes anyway. Also the ``not _HAVE_PALLAS``
    body of ``pallas_kernels.top_k_streaming`` (one home for the
    contract)."""
    n_items = item_factors.shape[0]
    k_eff = min(k, n_items)
    mask = None
    if exclude_idx is not None and exclude_idx.shape[1] > 0:
        excl = jnp.asarray(exclude_idx, jnp.int32)
        one_hot = jax.nn.one_hot(
            jnp.where(excl >= 0, excl, n_items), n_items + 1,
            dtype=jnp.bool_,
        ).any(axis=1)[:, :n_items]
        mask = one_hot
    scores, idx = top_k_for_vectors(
        query_vectors, item_factors, k_eff, exclude_mask=mask
    )
    # any -inf slot (excluded/invalid) carries the -1 index sentinel,
    # never a real (excluded) item id
    idx = jnp.where(jnp.isneginf(scores), -1, idx)
    if k_eff < k:
        scores = jnp.pad(
            scores, ((0, 0), (0, k - k_eff)), constant_values=NEG_INF
        )
        idx = jnp.pad(idx, ((0, 0), (0, k - k_eff)), constant_values=-1)
    return scores, idx


def resolve_topk_path(mode: str, b: int, n_items: int) -> str:
    """The resolved serve-side top-k path — "streaming" (Pallas fused
    kernel) or "dense" (XLA score + ``lax.top_k``). The ONE decision
    home: :func:`top_k_fused_vectors` dispatches on it and the serving
    templates record it (``/status.json`` → ``topkPath``), so the
    reported path can never drift from the executed one."""
    return "streaming" if use_streaming_topk(mode, b, n_items) else "dense"


def _fused_dispatch(query_vectors, item_factors, k, exclude_idx, mode):
    """Shared dispatch body of the fused entries (all jitted — the
    path decision and the streaming kernel's padding logic run at trace
    time, so a serving batch stays ONE device program like the dense
    kernels it replaces)."""
    path = resolve_topk_path(
        mode, query_vectors.shape[0], item_factors.shape[0]
    )
    if path == "streaming":
        from .pallas_kernels import top_k_streaming

        return top_k_streaming(query_vectors, item_factors, k, exclude_idx)
    return xla_topk_with_sentinels(
        query_vectors, item_factors, k, exclude_idx
    )


@functools.partial(jax.jit, static_argnames=("k", "mode"))
def top_k_fused_vectors(
    query_vectors: jax.Array,  # [B, R]
    item_factors: jax.Array,  # [I, R]
    k: int,
    exclude_idx: Optional[jax.Array] = None,  # [B, E] int32, -1 padded
    mode: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Fused score+select for raw query vectors. ``mode`` is the
    template-level ``streaming_top_k`` knob ("auto" | "always" |
    "never"), static like ``k`` so repeated serving calls hit the
    compilation cache."""
    return _fused_dispatch(query_vectors, item_factors, k, exclude_idx,
                           mode)


@functools.partial(jax.jit, static_argnames=("k", "mode"))
def top_k_for_users_fused(
    user_factors: jax.Array,  # [U, R]
    item_factors: jax.Array,  # [I, R]
    user_idx: jax.Array,  # [B] int32
    k: int,
    exclude_idx: Optional[jax.Array] = None,
    mode: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Fused top-k items for known users (the recommendation template's
    serving kernel): user-row gather stays on device inside the same
    program, and exclusions are per-query index lists instead of a
    dense ``[B, I]`` mask. The gather rides ``quant.ragged_gather`` —
    duplicate users in a batch (hot users under load) read their factor
    row once; bit-identical to the dense ``table[idx]`` it replaced."""
    return _fused_dispatch(
        ragged_gather(user_factors, user_idx),
        item_factors, k, exclude_idx, mode,
    )


@functools.partial(jax.jit, static_argnames=("k", "exclude_self", "mode"))
def top_k_similar_items_fused(
    item_factors: jax.Array,  # [I, R]
    item_idx: jax.Array,  # [B] int32
    k: int,
    exclude_self: bool = True,
    mode: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Fused cosine-similar items (the similarproduct kernel): the
    catalog normalization fuses into the same program (as the dense
    kernel's always did) and the query item's own index rides the
    streaming kernel's exclusion list — a [B, 1] index list instead of
    the dense ``[B, I]`` one-hot the unfused kernel builds. Note the
    sentinel contract difference from ``top_k_similar_items``: a sub-k
    slot here is (-inf, -1), not a real index with a -inf score."""
    item_factors = jnp.asarray(item_factors)
    norms = jnp.linalg.norm(item_factors, axis=1, keepdims=True)
    unit = item_factors / jnp.maximum(norms, 1e-12)
    idx = jnp.asarray(item_idx, jnp.int32)
    excl = idx[:, None] if exclude_self else None
    return _fused_dispatch(ragged_gather(unit, idx), unit, k, excl, mode)


def estimate_topk_hbm_bytes(
    b: int, n_items: int, rank: int, k: int, streaming: bool
) -> float:
    """HBM-traffic model for one batched top-k dispatch — the serve-side
    companion of ``ops.als.estimate_iteration_hbm_bytes`` (honest
    roofline accounting for the fused path, docs/performance.md#levers).

    Dense (XLA) path: read both factor inputs once, WRITE the [B, I]
    score matrix, re-read it for ``lax.top_k``, write [B, k] results
    (scores f32 + indices i32). Streaming path: the score tile lives in
    VMEM, so the matrix never touches HBM — item factors stream through
    once, queries and results are the only other traffic. Pinned by
    ``tests/test_als.py::TestTopkBytesModel``."""
    factors = float(b) * rank * 4.0 + float(n_items) * rank * 4.0
    results = float(b) * k * 8.0
    if streaming:
        return factors + results
    score_matrix = float(b) * n_items * 4.0
    return factors + 2.0 * score_matrix + results


@jax.jit
def standardize(scores: jax.Array) -> jax.Array:
    """Z-score standardization — the multi-algorithm ensemble combine step
    (reference similarproduct ``multi/`` Serving z-score + sum)."""
    mean = jnp.mean(scores)
    std = jnp.std(scores)
    return (scores - mean) / jnp.maximum(std, 1e-12)


# -- jit boundary telemetry (docs/observability.md#profiling) ---------------
#
# The serving dispatch is where a retrace hurts most: an unexpected
# shape reaching one of these kernels costs a fresh XLA compile inside a
# live request's latency budget (pad_pow2 exists to prevent exactly
# that). Routing every call through the process jit telemetry makes a
# pad_pow2 regression visible as pio_jit_retraces_total{fn=...} on the
# query server's /metrics instead of as an unexplained p99 cliff. The
# wrappers forward attributes, so `.lower()`-style AOT use keeps working.
from ..obs.profile import default_telemetry as _default_telemetry

top_k_for_users = _default_telemetry().wrap(
    "serving.topk_users", top_k_for_users
)
top_k_for_vectors = _default_telemetry().wrap(
    "serving.topk_vectors", top_k_for_vectors
)
top_k_similar_items = _default_telemetry().wrap(
    "serving.topk_similar", top_k_similar_items
)
top_k_fused_vectors = _default_telemetry().wrap(
    "serving.topk_fused", top_k_fused_vectors
)
top_k_for_users_fused = _default_telemetry().wrap(
    "serving.topk_users_fused", top_k_for_users_fused
)
top_k_similar_items_fused = _default_telemetry().wrap(
    "serving.topk_similar_fused", top_k_similar_items_fused
)
standardize = _default_telemetry().wrap("serving.standardize", standardize)
