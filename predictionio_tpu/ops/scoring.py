"""Serving-side scoring kernels.

The hot path of the deployed recommendation engine: the reference scores via
``MatrixFactorizationModel.recommendProducts`` (factor dot products, invoked
per query in ``examples/.../ALSAlgorithm.scala:76-80``); here queries are
batched into one gather → matmul → top-k device call
(SURVEY §3.2 "batched gather-dot kernel").

All kernels are jit'd with static k so repeated serving calls hit the
compilation cache; the query batch rides the mesh ``data`` axis when the
server shards a batch across chips.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# plain float, NOT jnp.float32(...): a module-level jnp scalar would
# initialize the device backend at import time (slow start-up for every
# CLI invocation, and a hang if the accelerator is unreachable)
NEG_INF = float("-inf")


def use_streaming_topk(mode: str, b_pad: int, n_items: int) -> bool:
    """Shared streaming-top-k selection rule for serving templates.

    Streaming (``pallas_kernels.top_k_streaming``) keeps the ``[B, I]``
    score matrix out of HBM entirely — mandatory for huge catalogs,
    pointless overhead for small ones. "auto" switches at ~1 GB of
    would-be scores on TPU (the XLA dense path is faster below that and
    the interpret-mode kernel is slow off-TPU). Raises on an unknown
    mode so a config typo fails at validation time, not mid-serving.
    """
    if mode not in ("auto", "always", "never"):
        raise ValueError(
            f"streaming_top_k must be 'auto', 'always' or 'never', "
            f"got {mode!r}"
        )
    if mode == "never":
        return False
    if mode == "always":
        return True
    import jax

    return jax.default_backend() == "tpu" and b_pad * n_items * 4 > (1 << 30)


def pad_pow2(n: int, lo: int = 1) -> int:
    """Smallest power of two >= max(n, lo).

    Serving shape-bucketing: micro-batched query batches arrive at every
    size from 1 to batch_max; dispatching each size directly would compile
    a fresh XLA program per size (20-40 s each on TPU). Padding batch and
    k to powers of two bounds the compile set to O(log) shapes."""
    n = max(int(n), lo)
    return 1 << (n - 1).bit_length()


def _score_topk(query_vectors, item_factors, k, exclude_mask):
    scores = jnp.einsum(
        "br,ir->bi", query_vectors, item_factors, preferred_element_type=jnp.float32
    )
    if exclude_mask is not None:
        scores = jnp.where(exclude_mask, NEG_INF, scores)
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_for_users(
    user_factors: jax.Array,  # [U, R]
    item_factors: jax.Array,  # [I, R]
    user_idx: jax.Array,  # [B] int32
    k: int,
    exclude_mask: Optional[jax.Array] = None,  # [B, I] bool — True = exclude
) -> Tuple[jax.Array, jax.Array]:
    """Top-k items for a batch of known users.

    Returns (scores [B, k], item indices [B, k]). ``exclude_mask`` implements
    the seen/unavailable-item filters the e-commerce template applies
    (reference ``ALSAlgorithm.scala`` in ecommerce template).
    """
    return _score_topk(user_factors[user_idx], item_factors, k, exclude_mask)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_for_vectors(
    query_vectors: jax.Array,  # [B, R]
    item_factors: jax.Array,  # [I, R]
    k: int,
    exclude_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k items for raw query vectors (cold-start / feature queries)."""
    return _score_topk(query_vectors, item_factors, k, exclude_mask)


@functools.partial(jax.jit, static_argnames=("k", "exclude_self"))
def top_k_similar_items(
    item_factors: jax.Array,  # [I, R]
    item_idx: jax.Array,  # [B] int32
    k: int,
    exclude_self: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Cosine-similar items — the similarproduct template's kernel
    (reference: ALS ``productFeatures`` cosine,
    ``examples/scala-parallel-similarproduct``).

    Returns (cosine scores [B, k], item indices [B, k]); when
    ``exclude_self`` the query item's own score is masked to -inf before
    the top-k selection.
    """
    norms = jnp.linalg.norm(item_factors, axis=1, keepdims=True)
    unit = item_factors / jnp.maximum(norms, 1e-12)
    q = unit[item_idx]  # [B, R]
    scores = jnp.einsum("br,ir->bi", q, unit, preferred_element_type=jnp.float32)
    if exclude_self:
        n_items = item_factors.shape[0]
        one_hot = jax.nn.one_hot(item_idx, n_items, dtype=jnp.bool_)
        scores = jnp.where(one_hot, NEG_INF, scores)
    return jax.lax.top_k(scores, k)


@jax.jit
def standardize(scores: jax.Array) -> jax.Array:
    """Z-score standardization — the multi-algorithm ensemble combine step
    (reference similarproduct ``multi/`` Serving z-score + sum)."""
    mean = jnp.mean(scores)
    std = jnp.std(scores)
    return (scores - mean) / jnp.maximum(std, 1e-12)


# -- jit boundary telemetry (docs/observability.md#profiling) ---------------
#
# The serving dispatch is where a retrace hurts most: an unexpected
# shape reaching one of these kernels costs a fresh XLA compile inside a
# live request's latency budget (pad_pow2 exists to prevent exactly
# that). Routing every call through the process jit telemetry makes a
# pad_pow2 regression visible as pio_jit_retraces_total{fn=...} on the
# query server's /metrics instead of as an unexplained p99 cliff. The
# wrappers forward attributes, so `.lower()`-style AOT use keeps working.
from ..obs.profile import default_telemetry as _default_telemetry

top_k_for_users = _default_telemetry().wrap(
    "serving.topk_users", top_k_for_users
)
top_k_for_vectors = _default_telemetry().wrap(
    "serving.topk_vectors", top_k_for_vectors
)
top_k_similar_items = _default_telemetry().wrap(
    "serving.topk_similar", top_k_similar_items
)
standardize = _default_telemetry().wrap("serving.standardize", standardize)
