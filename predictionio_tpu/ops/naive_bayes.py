"""Categorical Naive Bayes on TPU.

Rebuild of the reference's pure-Spark engine library classifier
(``e2/src/main/scala/io/prediction/e2/engine/CategoricalNaiveBayes.scala:23-166``).
The reference folds string-categorical feature counts with ``combineByKey``
over RDD partitions and keeps the model as nested ``Map[String, ...]``.

TPU-first restatement: string labels/features are indexed through host-side
vocabularies once, then the sufficient statistics — label counts and
per-slot (label, value) co-occurrence counts — are one-hot scatter-adds on
device. The model is a dense pytree:

- ``log_priors``      [L]        — log P(label)
- ``log_likelihoods`` [F, L, V]  — log P(value | label) per feature slot,
  padded to the max slot vocabulary (padding cells hold ``-inf``; they are
  unreachable through the vocab mapping)

so scoring a batch of points is two gathers + a sum on the MXU-friendly
dense tables, and the count reduction is a ``psum`` across a data-sharded
mesh instead of a shuffle (SURVEY §2.8: combineByKey → scatter-add + psum).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = float("-inf")


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """``LabeledPoint(label, features)``
    (``CategoricalNaiveBayes.scala:152-166``)."""

    label: str
    features: Tuple[str, ...]

    def __post_init__(self):
        object.__setattr__(self, "features", tuple(self.features))


def _counts(
    label_ids: np.ndarray,  # [N]
    feature_ids: np.ndarray,  # [N, F]
    n_labels: int,
    vocab_sizes: Sequence[int],
) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
    """Label counts [L] and per-slot (label, value) counts [L, V_f] via
    device scatter-adds (the combineByKey replacement)."""
    v_max = max(vocab_sizes)
    f = feature_ids.shape[1]

    @jax.jit
    def compute(lids, fids):
        label_counts = jnp.zeros((n_labels,), jnp.float32).at[lids].add(1.0)
        # one scatter over a [F, L, Vmax] cube: index (slot, label, value)
        slots = jnp.broadcast_to(jnp.arange(f)[None, :], fids.shape)
        cube = jnp.zeros((f, n_labels, v_max), jnp.float32)
        cube = cube.at[
            slots.reshape(-1),
            jnp.broadcast_to(lids[:, None], fids.shape).reshape(-1),
            fids.reshape(-1),
        ].add(1.0)
        return label_counts, cube

    label_counts, cube = compute(
        jnp.asarray(label_ids, jnp.int32), jnp.asarray(feature_ids, jnp.int32)
    )
    return label_counts, [cube[i, :, : vocab_sizes[i]] for i in range(f)]


@dataclasses.dataclass
class CategoricalNaiveBayesModel:
    """Dense-table NB model (``CategoricalNaiveBayesModel``,
    ``CategoricalNaiveBayes.scala:88-146``).

    ``label_vocab`` / ``feature_vocabs`` map the string space to table
    indices; unseen feature values fall back to ``default_likelihood`` at
    score time (reference default: -inf).
    """

    label_vocab: Dict[str, int]
    feature_vocabs: List[Dict[str, int]]
    log_priors: np.ndarray  # [L]
    log_likelihoods: List[np.ndarray]  # per slot [L, V_f]

    @property
    def labels(self) -> List[str]:
        out = [""] * len(self.label_vocab)
        for name, i in self.label_vocab.items():
            out[i] = name
        return out

    @property
    def feature_count(self) -> int:
        return len(self.feature_vocabs)

    def _slot_scores(
        self,
        features: Sequence[str],
        default_likelihood: Callable[[Sequence[float]], float],
    ) -> np.ndarray:
        """Per-label summed log likelihoods [L] with unseen-value fallback."""
        n_labels = len(self.label_vocab)
        total = np.zeros((n_labels,), np.float64)
        for slot, value in enumerate(features):
            table = self.log_likelihoods[slot]
            idx = self.feature_vocabs[slot].get(value)
            if idx is None:
                # per-label fallback over that label's known likelihoods
                for li in range(n_labels):
                    row = table[li]
                    finite = row[np.isfinite(row)]
                    total[li] += default_likelihood(list(finite))
            else:
                total += table[:, idx]
        return total

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[Sequence[float]], float] = lambda ls: NEG_INF,
    ) -> Optional[float]:
        """Log score of (label, features); None for unknown labels
        (``CategoricalNaiveBayes.scala:104-121``)."""
        li = self.label_vocab.get(point.label)
        if li is None:
            return None
        scores = self._slot_scores(point.features, default_likelihood)
        return float(self.log_priors[li] + scores[li])

    def predict(self, features: Sequence[str]) -> str:
        """Highest-scoring label (``CategoricalNaiveBayes.scala:139-146``)."""
        scores = self._slot_scores(features, lambda ls: NEG_INF)
        best = int(np.argmax(self.log_priors + scores))
        return self.labels[best]

    def predict_batch(self, feature_ids: np.ndarray) -> np.ndarray:
        """Vectorized device path: pre-indexed features [N, F] → label ids
        [N] (one fused gather+sum+argmax; the serving-side analogue)."""
        v_max = max(t.shape[1] for t in self.log_likelihoods)
        tables = jnp.stack(
            [
                jnp.pad(
                    jnp.asarray(t),
                    ((0, 0), (0, v_max - t.shape[1])),
                    constant_values=NEG_INF,
                )
                for t in self.log_likelihoods
            ]
        )  # [F, L, Vmax]
        priors = jnp.asarray(self.log_priors)

        @jax.jit
        def run(fids):
            # gather per slot: scores[n, f, l] = tables[f, l, fids[n, f]]
            g = jnp.take_along_axis(
                tables[None],  # [1, F, L, V]
                fids[:, :, None, None],  # [N, F, 1, 1]
                axis=3,
            )[..., 0]  # [N, F, L]
            return jnp.argmax(priors[None] + g.sum(axis=1), axis=1)

        return np.asarray(run(jnp.asarray(feature_ids, jnp.int32)))


def _build_vocab(values: Sequence[str]) -> Dict[str, int]:
    vocab: Dict[str, int] = {}
    for v in values:
        if v not in vocab:
            vocab[v] = len(vocab)
    return vocab


def train(points: Sequence[LabeledPoint]) -> CategoricalNaiveBayesModel:
    """Train from labeled points (``CategoricalNaiveBayes.train``,
    ``CategoricalNaiveBayes.scala:29-80``): priors = log(count_l / N),
    likelihoods = log(count_{l,v} / count_l); zero-count cells are -inf
    (the reference simply has no map entry)."""
    if not points:
        raise ValueError("Cannot train Naive Bayes on an empty dataset")
    n_features = len(points[0].features)
    for p in points:
        if len(p.features) != n_features:
            raise ValueError(
                "All points must have the same number of feature slots"
            )

    label_vocab = _build_vocab([p.label for p in points])
    feature_vocabs = [
        _build_vocab([p.features[i] for p in points]) for i in range(n_features)
    ]
    label_ids = np.array([label_vocab[p.label] for p in points], np.int32)
    feature_ids = np.array(
        [
            [feature_vocabs[i][p.features[i]] for i in range(n_features)]
            for p in points
        ],
        np.int32,
    )

    label_counts, slot_counts = _counts(
        label_ids,
        feature_ids,
        len(label_vocab),
        [len(v) for v in feature_vocabs],
    )
    label_counts_np = np.asarray(label_counts)
    n = float(label_counts_np.sum())
    with np.errstate(divide="ignore"):
        log_priors = np.log(label_counts_np / n)
        log_likelihoods = [
            np.log(np.asarray(c) / label_counts_np[:, None]) for c in slot_counts
        ]
    return CategoricalNaiveBayesModel(
        label_vocab=label_vocab,
        feature_vocabs=feature_vocabs,
        log_priors=log_priors,
        log_likelihoods=log_likelihoods,
    )
