"""Alternating Least Squares on TPU.

The compute-plane replacement for the reference's delegation to Spark MLlib
``ALS.train`` (invoked from the recommendation templates, e.g.
``examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/
ALSAlgorithm.scala:56-62``; SURVEY §2.8 maps MLlib's block-partitioned factors
to mesh-sharded factor tables).

Semantics follow MLlib 1.2's explicit-feedback ALS (ALS-WR): per-row normal
equations ``(Yᵀ_u Y_u + λ·n_u·I) x_u = Yᵀ_u r_u`` with the regularizer scaled
by the row's rating count, and the implicit-preference variant (Hu-Koren-
Volinsky) with confidence ``c = 1 + α·r`` using the precomputed global
``YᵀY``.

TPU mapping
-----------
Ratings are CSR-like, grouped into **degree buckets** (ALX, arXiv:2112.02194):
every row in a bucket is padded to the bucket's width K, so each bucket is a
dense ``[B, K]`` problem — static shapes for XLA, gathers + batched matmuls on
the MXU, batched Cholesky solves. A Python loop over buckets issues a few
jit-compiled shapes; inside a bucket, rows stream through fixed-size blocks.

Sharding: the row dimension (users or items being solved) is sharded over the
mesh ``data`` axis; the opposite factor table is replicated (all-gathered by
XLA when the side switches). For factor tables too big to replicate, pass a
``model``-sharded table and XLA turns the gather into an all-to-all — the
mesh layout, not this code, decides the collective pattern.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: Default degree-bucket widths (powers of 4; rows pad to the nearest).
DEFAULT_BUCKET_WIDTHS = (8, 32, 128, 512, 2048, 8192, 32768)

#: Max rows per device block inside a bucket solve (bounds peak gather
#: memory). Small buckets allocate LESS than a full block — see
#: :func:`_alloc_block`: sentinel padding rows cost real device FLOPs.
_BLOCK_ROWS = {8: 16384, 32: 8192, 128: 4096, 512: 1024, 2048: 256, 8192: 64, 32768: 16}


@dataclasses.dataclass
class Bucket:
    """One padded degree bucket: ``rows[i]`` has its ratings in
    ``idx/val[i, :counts[i]]``.

    When built with ``pad_to_blocks=True`` the bucket additionally carries
    whole padding rows (``rows == n_rows`` sentinel, ``counts == 0``) so
    :func:`stage` can ship the slabs without re-padding copies.
    """

    rows: np.ndarray  # [B] int32 — row ids in the full matrix
    idx: np.ndarray  # [B, K] int32/uint16 — column indices (0-padded)
    val: np.ndarray  # [B, K] float32 — ratings (0-padded)
    counts: np.ndarray  # [B] int32 — valid entries per row (<= K)

    @property
    def width(self) -> int:
        return self.idx.shape[1]

    @property
    def mask(self) -> np.ndarray:
        """[B, K] float32 validity mask, derived on demand — ratings are
        prefix-packed, so the mask is a pure function of ``counts``."""
        return (
            np.arange(self.width, dtype=np.int32)[None, :]
            < self.counts[:, None]
        ).astype(np.float32)


@dataclasses.dataclass
class BucketedMatrix:
    """One side of the rating matrix (by-row = by-user or by-item)."""

    n_rows: int
    n_cols: int
    nnz: int
    buckets: List[Bucket]


def bucketize(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    n_cols: int,
    bucket_widths: Sequence[int] = DEFAULT_BUCKET_WIDTHS,
    pad_to_blocks: bool = False,
) -> BucketedMatrix:
    """COO → degree-bucketed padded CSR.

    Rows with degree above the largest width are truncated to it (keeping
    the first ratings in input order) — with the default widths this only
    triggers beyond 32768 ratings per row.

    ``pad_to_blocks=True`` allocates each bucket's slabs rounded up to the
    device chunk size (``_BLOCK_ROWS``) with sentinel padding rows, so
    :func:`stage` ships them zero-copy — the training fast path. Column
    indices are uint16 whenever ``n_cols`` fits (half the transfer bytes).

    Dispatches to the native (C++ threaded O(nnz) scatter,
    ``native/bucketize.cc``) or the numpy (argsort-based) implementation;
    both produce bit-identical arrays. ``PIO_NO_NATIVE_BUCKETIZE=1`` forces
    the numpy path; a missing toolchain falls back silently.
    """
    nnz = len(rows)
    if nnz >= 2**31 or n_rows >= 2**31 or n_cols >= 2**31:
        raise ValueError("bucketize supports up to 2^31-1 ratings/ids")
    rows = np.ascontiguousarray(np.asarray(rows), dtype=np.int32)
    cols = np.ascontiguousarray(np.asarray(cols), dtype=np.int32)
    vals = np.ascontiguousarray(np.asarray(vals), dtype=np.float32)
    import os as _os

    global _NATIVE_BUCKETIZE_BROKEN
    if (
        nnz
        and not _NATIVE_BUCKETIZE_BROKEN
        and _os.environ.get("PIO_NO_NATIVE_BUCKETIZE") != "1"
    ):
        from ..native import NativeBuildError

        try:
            return _bucketize_native(
                rows, cols, vals, n_rows, n_cols, bucket_widths,
                pad_to_blocks,
            )
        except NativeBuildError as exc:
            # Toolchain-less host: numpy is full parity. Cache the verdict
            # so we don't re-spawn a doomed compiler on every call; any
            # OTHER failure propagates — a native-path bug must not become
            # a silent slowdown.
            import logging

            logging.getLogger(__name__).warning(
                "native bucketize unavailable, using numpy path: %s", exc
            )
            _NATIVE_BUCKETIZE_BROKEN = True
    return _bucketize_numpy(
        rows, cols, vals, n_rows, n_cols, bucket_widths, pad_to_blocks
    )


#: Set after the first failed native-bucketize build (per process).
_NATIVE_BUCKETIZE_BROKEN = False


def _idx_dtype(n_cols: int):
    """Staged column-index dtype: uint16 when the opposite-side id space
    fits (halves the largest slab's bytes), else int32. Single source of
    truth for bucketize (both paths), stage, and the C++ fill's
    caller-guarantee."""
    return np.uint16 if n_cols <= 0xFFFF else np.int32


def _alloc_block(width: int, n_real: int) -> int:
    """Row-allocation granularity for one bucket: the smaller of the
    width's :data:`_BLOCK_ROWS` bound (peak gather memory) and the
    power-of-two envelope of the bucket's real row count (floor 8, the
    sublane granularity).

    Sentinel padding rows are not free — the solve einsums compute over
    them — and allocating a FULL device block regardless of occupancy
    made small workloads mostly padding: at the bench's CPU-fallback
    scale the widest buckets carried 1–7 real rows in 16–64-row blocks
    (74–99% wasted FLOPs, measured round 12). Right-sizing to a power
    of two keeps the compiled-program set O(log) per width (the serving
    ``pad_pow2`` discipline) while the block bound still caps the
    gather working set for full buckets."""
    block = _block_rows_for(int(width))
    if n_real <= 0:
        return block
    pow2 = 1 << (max(int(n_real), 8) - 1).bit_length()
    return min(block, pow2)


def _alloc_rows(sel, counts_clip, n_rows, width, pad_to_blocks):
    """Rows/counts arrays for one bucket, optionally rounded up to the
    device chunk size with (n_rows, 0) sentinel padding rows. Empty
    buckets stay empty (they are dropped later; padding them would zero a
    whole block-sized slab for nothing)."""
    b = len(sel)
    if not pad_to_blocks or b == 0:
        return sel, counts_clip, b
    block = _alloc_block(int(width), b)
    b_alloc = -(-b // block) * block
    rows_arr = np.full(b_alloc, n_rows, dtype=np.int32)
    rows_arr[:b] = sel
    cnt = np.zeros(b_alloc, dtype=np.int32)
    cnt[:b] = counts_clip
    return rows_arr, cnt, b_alloc


def _bucketize_native(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    n_cols: int,
    bucket_widths: Sequence[int] = DEFAULT_BUCKET_WIDTHS,
    pad_to_blocks: bool = False,
) -> BucketedMatrix:
    """Threaded two-pass scatter (no sort): numpy computes the O(n_rows)
    bucket/slot assignment, C++ fills the padded slabs deterministically."""
    import ctypes

    from ..native import load_library

    lib = load_library("bucketize")
    lib.pio_bucketize_fill.restype = ctypes.c_int

    nnz = len(rows)
    widths = np.asarray(sorted(bucket_widths), dtype=np.int32)
    max_w = int(widths[-1])
    idx_dtype = _idx_dtype(n_cols)
    counts = np.bincount(rows, minlength=n_rows).astype(np.int32)
    present = np.nonzero(counts)[0].astype(np.int32)  # ascending row ids
    assignment = np.searchsorted(
        widths, np.minimum(counts[present], max_w), side="left"
    )

    bucket_of = np.zeros(n_rows, dtype=np.int32)
    slot_of = np.zeros(n_rows, dtype=np.int32)
    slabs = []  # (sel, counts, b_alloc, idx, val) per width, empties too
    for wi, width in enumerate(widths):
        sel = present[assignment == wi]
        bucket_of[sel] = wi
        slot_of[sel] = np.arange(len(sel), dtype=np.int32)
        cnt = np.minimum(counts[sel], int(width)).astype(np.int32)
        rows_arr, cnt, b_alloc = _alloc_rows(
            sel, cnt, n_rows, width, pad_to_blocks
        )
        slabs.append(
            (
                rows_arr,
                cnt,
                np.zeros(b_alloc * width, dtype=idx_dtype),
                np.zeros(b_alloc * width, dtype=np.float32),
                len(sel),
            )
        )

    i32p, f32p = ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float)
    voidp = ctypes.c_void_p
    idx_ptrs = (voidp * len(widths))(
        *[s[2].ctypes.data_as(voidp) for s in slabs]
    )
    val_ptrs = (f32p * len(widths))(
        *[s[3].ctypes.data_as(f32p) for s in slabs]
    )
    rc = lib.pio_bucketize_fill(
        rows.ctypes.data_as(i32p),
        cols.ctypes.data_as(i32p),
        vals.ctypes.data_as(f32p),
        ctypes.c_int64(nnz),
        ctypes.c_int64(n_rows),
        bucket_of.ctypes.data_as(i32p),
        slot_of.ctypes.data_as(i32p),
        widths.ctypes.data_as(i32p),
        ctypes.c_int32(len(widths)),
        idx_ptrs,
        val_ptrs,
        ctypes.c_int32(1 if idx_dtype == np.uint16 else 0),
    )
    if rc != 0:
        raise RuntimeError(f"pio_bucketize_fill failed rc={rc}")

    buckets = [
        Bucket(
            rows=rows_arr,
            idx=idx.reshape(len(rows_arr), int(w)),
            val=val.reshape(len(rows_arr), int(w)),
            counts=cnt,
        )
        for w, (rows_arr, cnt, idx, val, n_present) in zip(widths, slabs)
        if n_present
    ]
    return BucketedMatrix(
        n_rows=n_rows, n_cols=n_cols, nnz=int(nnz), buckets=buckets
    )


def _bucketize_numpy(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    n_cols: int,
    bucket_widths: Sequence[int] = DEFAULT_BUCKET_WIDTHS,
    pad_to_blocks: bool = False,
) -> BucketedMatrix:
    """Pure-numpy reference implementation (argsort-based).

    Host-bandwidth-tuned: int32 temporaries throughout (valid while nnz and
    row ids fit in 31 bits), group boundaries from a diff instead of
    ``np.unique``, and validity kept as per-row counts instead of a
    materialized mask.
    """
    nnz = len(rows)
    idx_dtype = _idx_dtype(n_cols)
    order = np.argsort(rows, kind="stable")  # radix for int keys
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    if nnz:
        boundary = np.nonzero(np.diff(rows_s))[0].astype(np.int64) + 1
        start = np.concatenate([[0], boundary])
        uniq = rows_s[start]
    else:
        start = np.zeros(0, dtype=np.int64)
        uniq = rows_s
    counts = np.diff(np.append(start, nnz))

    buckets: List[Bucket] = []
    widths = sorted(bucket_widths)
    max_w = widths[-1]
    degrees = np.minimum(counts, max_w)
    # assign each row to the smallest width >= degree
    assignment = np.searchsorted(widths, degrees, side="left")

    for wi, width in enumerate(widths):
        sel = np.nonzero(assignment == wi)[0]
        if sel.size == 0:
            continue
        b = sel.size
        c = np.minimum(counts[sel], width).astype(np.int32)
        rows_arr, cnt, b_alloc = _alloc_rows(
            uniq[sel].astype(np.int32), c, n_rows, width, pad_to_blocks
        )
        total = int(c.sum())
        # within-row offsets [0..c0), [0..c1), … concatenated (vectorized)
        cum = np.cumsum(c, dtype=np.int32)
        within = np.arange(total, dtype=np.int32) - np.repeat(cum - c, c)
        src = np.repeat(start[sel].astype(np.int32), c) + within
        dst = np.repeat(
            (np.arange(b, dtype=np.int64) * width).astype(np.int64), c
        ) + within
        idx = np.zeros(b_alloc * width, dtype=idx_dtype)
        val = np.zeros(b_alloc * width, dtype=np.float32)
        idx[dst] = cols_s[src].astype(idx_dtype)
        val[dst] = vals_s[src]
        buckets.append(
            Bucket(
                rows=rows_arr,
                idx=idx.reshape(b_alloc, width),
                val=val.reshape(b_alloc, width),
                counts=cnt,
            )
        )
    return BucketedMatrix(
        n_rows=n_rows, n_cols=n_cols, nnz=int(nnz), buckets=buckets
    )


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    """MLlib-compatible knobs (``ALS.train`` signature)."""

    rank: int = 10
    iterations: int = 10
    lambda_: float = 0.01
    implicit_prefs: bool = False
    alpha: float = 1.0  # implicit confidence scale
    seed: int = 0
    #: "auto" (default) resolves at train time: "pallas" on TPU with
    #: rank <= 80 (single-chip or mesh — under a mesh the kernel runs
    #: per-device inside shard_map over the data axis), else "chunked".
    #: "chunked" fuses each block's Cholesky into the chunk map;
    #: "two_phase" batches one Cholesky per bucket (measured slower than
    #: chunked on v5e); "pallas" replaces XLA's batched Cholesky with
    #: the fused transposed-layout kernel
    #: (ops/pallas_kernels.spd_solve_t, ~25× on the solve stage). All
    #: modes produce identical results up to float reassociation.
    solve_mode: str = "auto"
    #: "f32" (default) or "bf16": dtype of the gathered opposite-side
    #: factors feeding the normal-equation einsums (accumulation stays
    #: f32). bf16 halves the gather's HBM bytes and doubles MXU rate at
    #: ~0.4% relative input rounding — the λ·n_u ridge keeps the solves
    #: stable, but quality-gate the result (RMSE) before adopting.
    gather_dtype: str = "f32"
    #: Sort each solve row's gathered column indices ascending before
    #: staging (host-side, one vectorized argsort per bucket). The
    #: Gramian sum over K is permutation-invariant, so results are
    #: identical up to float reassociation (the ROUND7_NOTES contract:
    #: factors to rtol 1e-3 / atol 1e-4 over 3 iterations, training RMSE
    #: to 1e-3 — pinned in tests/test_als.py); what changes is HBM
    #: access locality — adjacent gathers hit adjacent factor rows.
    #: ``None`` (the default) resolves to True when the inputs are
    #: host-side :class:`BucketedMatrix` (the sort happens pre-staging)
    #: and False for already-staged inputs, which cannot be reordered.
    #: Pass ``False`` explicitly to opt out (the legacy unsorted path);
    #: an explicit ``True`` with staged inputs still fails loudly.
    sort_gather_indices: Optional[bool] = None
    #: Build the normal equations with the fused gather+Gramian Pallas
    #: kernel (``ops/pallas_kernels.gramian_fused``) instead of the XLA
    #: gather + einsum: factor rows stream HBM→VMEM exactly once and the
    #: ``[B, K, R]`` gathered intermediate never exists (~3× less
    #: gather-stage HBM traffic by the PERF.md accounting). ``None``
    #: (the default) resolves to True exactly when ``solve_mode``
    #: resolves to "pallas" (the fused build shares that kernel family's
    #: VMEM envelope); pass ``False`` explicitly to opt out (the
    #: einsum-built legacy path). An explicit ``True`` with a
    #: non-pallas solve mode still fails loudly — a silently ignored
    #: flag would corrupt the hardware A/B.
    fused_gather: Optional[bool] = None

    def resolve_levers(self, staged_inputs: bool = False) -> dict:
        """The CONCRETE lever settings a train run with this config will
        execute — ``None`` tri-states resolved against the backend
        (``solve_mode="auto"``) and the input form (``staged_inputs``).
        One home for the resolution rules, shared by :func:`als_train`
        and the bench/ledger accounting ("record resolved, not
        requested" — docs/performance.md#levers)."""
        solve_mode = self.solve_mode
        if solve_mode == "auto":
            solve_mode = (
                "pallas"
                if (self.rank <= 80 and jax.default_backend() == "tpu")
                else "chunked"
            )
        sort = self.sort_gather_indices
        if sort is None:
            sort = not staged_inputs
        fused = self.fused_gather
        if fused is None:
            fused = solve_mode == "pallas"
        return {
            "solve_mode": solve_mode,
            "gather_dtype": self.gather_dtype,
            "sort_gather": bool(sort),
            "fused_gather": bool(fused),
        }


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------
def _system_explicit_g(g, val, mask, lam, rank):
    """Normal equations from ALREADY-GATHERED masked factors ``g``
    [B, K, R] — the math half of :func:`_system_explicit`, split out so
    the sharded trainer's pipelined off-shard gathers
    (``ops/als_sharded.py``) can issue the gather separately from the
    solve it feeds."""
    # Batched Gramian: MXU matmul [B, R, K] @ [B, K, R]
    a = jnp.einsum("bkr,bks->brs", g, g, preferred_element_type=jnp.float32)
    n_u = mask.astype(jnp.float32).sum(axis=1)  # [B]
    a = a + (lam * n_u)[:, None, None] * jnp.eye(rank, dtype=jnp.float32)
    b = jnp.einsum(
        "bkr,bk->br", g, val.astype(g.dtype),
        preferred_element_type=jnp.float32,
    )
    return a, b


def _system_explicit(y, idx, val, mask, lam, rank):
    """Normal equations for one row block (traceable body).

    y: [N, R] opposite factors (its dtype — f32 or bf16 — sets the gather
    and MXU input precision; accumulation is always f32); idx/val/mask:
    [B, K] with mask matching y's dtype.
    A_u = Gᵀ G + λ n_u I,  b_u = Gᵀ r_u   (G = masked gathered factors)
    """
    g = y[idx] * mask[..., None]  # [B, K, R]
    return _system_explicit_g(g, val, mask, lam, rank)


def _system_implicit_g(g, yty, val, mask, lam, alpha, rank):
    """Implicit-feedback normal equations from already-gathered masked
    factors ``g`` [B, K, R] (see :func:`_system_explicit_g`)."""
    maskf = mask.astype(jnp.float32)
    c_minus_1 = (alpha * jnp.abs(val)) * maskf  # [B, K]
    pref = (val > 0).astype(jnp.float32) * maskf  # [B, K]
    a = yty[None] + jnp.einsum(
        "bkr,bk,bks->brs", g, c_minus_1.astype(g.dtype), g,
        preferred_element_type=jnp.float32,
    )
    n_u = maskf.sum(axis=1)
    a = a + (lam * n_u)[:, None, None] * jnp.eye(rank, dtype=jnp.float32)
    b = jnp.einsum(
        "bkr,bk->br", g, ((1.0 + c_minus_1) * pref).astype(g.dtype),
        preferred_element_type=jnp.float32,
    )
    return a, b


def _system_implicit(y, yty, idx, val, mask, lam, alpha, rank):
    """Implicit-feedback normal equations (Hu-Koren-Volinsky, MLlib
    semantics).

    A_u = YᵀY + Σ_observed (c-1) y yᵀ + λ n_u I,  b_u = Σ_observed c·p·y
    with confidence c = 1 + α·|r| and preference p = 1[r > 0] (MLlib's
    ``ALS.scala`` implicit convention: confidence from magnitude, preference
    from sign — a negative rating is high-confidence "not preferred").
    """
    g = y[idx] * mask[..., None]  # [B, K, R]
    return _system_implicit_g(g, yty, val, mask, lam, alpha, rank)


def _cho_solve(a, b):
    chol = jax.scipy.linalg.cho_factor(a, lower=True)
    return jax.scipy.linalg.cho_solve(chol, b)


@dataclasses.dataclass
class _StagedBucket:
    """Bucket tensors resident on device, pre-chunked along a leading C axis.

    The [B, K] validity mask is NOT transferred: it is a pure function of
    the per-row rating count, so only ``counts`` ([C, B] int32) crosses
    host→device and the mask is rebuilt inside the traced solve — a third
    of the staging bytes, which on a remote-tunnel device is wall-clock."""

    rows: jax.Array  # [C, B] int32 (padded with n_rows → dropped by scatter)
    idx: jax.Array  # [C, B, K] int32, or uint16 when n_cols <= 0xFFFF
    #                 (transfer packing; widened in _solve_side_traced)
    val: jax.Array  # [C, B, K] float32
    counts: jax.Array  # [C, B] int32 — ratings per row (0 on padding)


@dataclasses.dataclass
class StagedMatrix:
    """One side staged on device — transferred once, reused every iteration."""

    n_rows: int
    n_cols: int
    nnz: int
    buckets: List[_StagedBucket]


def _block_rows_for(width: int) -> int:
    for w, b in _BLOCK_ROWS.items():
        if w == width:
            return b
    # unseen width: bound gather chunk to ~64M floats
    return max(16, (1 << 26) // max(1, width * 64))


def stage(
    side: BucketedMatrix, sharding=None, row_multiple: int = 1
) -> StagedMatrix:
    """Move a bucketed matrix to device in chunked layout.

    ``sharding`` (optional ``jax.sharding.Sharding``) shards the block-row
    dimension — the rows being solved — across the mesh data axis;
    ``row_multiple`` rounds the block size up so the sharded dim divides
    evenly over the axis.

    Buckets built with ``bucketize(..., pad_to_blocks=True)`` are already
    chunk-aligned with uint16 indices where applicable: this function then
    only reshapes views and issues the async ``device_put`` — no host
    copies (the copies were ~the whole staging wall-clock on a 1-core
    host).
    """
    staged = []
    for bucket in side.buckets:
        # same right-sizing rule as _alloc_rows: a bucket already padded
        # by bucketize(pad_to_blocks=True) re-chunks to its own size (no
        # re-padding back up to a full block), an unpadded one pads to
        # its pow2 envelope
        n = bucket.rows.shape[0]
        block = _alloc_block(bucket.width, n)
        if row_multiple > 1:
            block = ((block + row_multiple - 1) // row_multiple) * row_multiple
        n_chunks = max(1, (n + block - 1) // block)
        padded = n_chunks * block
        pad = padded - n

        rows, idx, val, counts = (
            bucket.rows, bucket.idx, bucket.val, bucket.counts,
        )
        if pad:
            # rows pad with n_rows sentinel → dropped by the mode="drop"
            # scatter in the solve
            rows = np.pad(rows, (0, pad), constant_values=side.n_rows)
            idx = np.pad(idx, ((0, pad), (0, 0)))
            val = np.pad(val, ((0, pad), (0, 0)))
            counts = np.pad(counts, (0, pad))
        target_dtype = _idx_dtype(side.n_cols)
        if idx.dtype != target_dtype and target_dtype == np.uint16:
            # column ids fit uint16: halves the largest staged tensor's
            # host→device bytes (widened back to int32 inside the traced
            # solve, where the cast fuses for free)
            idx = idx.astype(np.uint16)
        put = (
            (lambda a: jax.device_put(a, sharding))
            if sharding is not None
            else jax.device_put
        )
        staged.append(
            _StagedBucket(
                rows=put(rows.reshape(n_chunks, block)),
                idx=put(idx.reshape(n_chunks, block, bucket.width)),
                val=put(val.reshape(n_chunks, block, bucket.width)),
                counts=put(counts.reshape(n_chunks, block)),
            )
        )
    return StagedMatrix(
        n_rows=side.n_rows, n_cols=side.n_cols, nnz=side.nnz, buckets=staged
    )


def _update_side(
    y: jax.Array,
    side,
    cfg: ALSConfig,
    x_shape: Tuple[int, int],
    yty: Optional[jax.Array],
) -> jax.Array:
    """Solve all rows of one side given the opposite factors ``y`` — a thin
    dispatch over the same traced body the training iteration uses."""
    if isinstance(side, BucketedMatrix):
        side = stage(side)
    return _solve_side_traced(
        y,
        _bucket_tensors(side),
        x_shape[0],
        cfg.rank,
        cfg.implicit_prefs,
        jnp.float32(cfg.lambda_),
        jnp.float32(cfg.alpha),
        yty,
    )


def init_factors(n: int, rank: int, seed: int) -> jax.Array:
    """MLlib-style init: |N(0,1)| / sqrt(rank) keeps initial predictions
    positive and O(1)."""
    key = jax.random.PRNGKey(seed)
    return jnp.abs(jax.random.normal(key, (n, rank), dtype=jnp.float32)) / jnp.sqrt(
        jnp.float32(rank)
    )


def sort_bucket_indices(side: BucketedMatrix) -> BucketedMatrix:
    """Reorder each row's valid (idx, val) pairs ascending by column index.

    Gather locality: the normal-equation build gathers one opposite-side
    factor row (~rank·4 B) per index; sorted indices turn a random walk
    over the factor table into segment-local accesses. The per-row sum is
    permutation-invariant, so the solve result is unchanged up to float
    reassociation. Padding (entries at positions >= counts[i]) keeps its
    place at the row tail — the counts-based validity mask depends on it.
    """
    out = []
    for b in side.buckets:
        n, k = b.idx.shape
        if n == 0 or k <= 1:
            out.append(b)
            continue
        pos = np.arange(k, dtype=np.int64)[None, :]
        key = np.where(
            pos < b.counts[:, None].astype(np.int64),
            b.idx.astype(np.int64),
            np.iinfo(np.int64).max,
        )
        order = np.argsort(key, axis=1, kind="stable")
        out.append(
            dataclasses.replace(
                b,
                idx=np.take_along_axis(b.idx, order, axis=1),
                val=np.take_along_axis(b.val, order, axis=1),
            )
        )
    return dataclasses.replace(side, buckets=out)


@dataclasses.dataclass
class ALSFactors:
    """Trained factor tables (the ``MatrixFactorizationModel`` analogue)."""

    user_factors: jax.Array  # [n_users, rank]
    item_factors: jax.Array  # [n_items, rank]
    rank: int


def _bucket_tensors(side: StagedMatrix):
    return tuple((b.rows, b.idx, b.val, b.counts) for b in side.buckets)


def _fused_chunk_solve(
    y_pad, yty_pad, lam, alpha, idx_blk, val_blk, counts_blk,
    *, implicit, rank,
):
    """One chunk's normal equations + SPD solve on the fused Pallas path —
    per-device logic only (no mesh handling): under a mesh the caller
    wraps this whole function in ``shard_map`` over the data axis, so the
    ``[B, K, R]`` gathered intermediate never exists on any device.

    ``yty_pad`` is always an array (zeros in explicit mode) so the
    function is shard_map-able without closures over tracers.
    """
    from .pallas_kernels import _SPD_BLK, gramian_fused, spd_solve_t

    k = idx_blk.shape[-1]
    maskf = (
        jnp.arange(k, dtype=jnp.int32)[None, :] < counts_blk[:, None]
    ).astype(jnp.float32)
    if implicit:
        c1 = (alpha * jnp.abs(val_blk)) * maskf
        w2 = c1
        rhs = (1.0 + c1) * ((val_blk > 0).astype(jnp.float32) * maskf)
        yty_arg = yty_pad
    else:
        w2 = maskf
        rhs = val_blk * maskf
        yty_arg = None
    ridge = lam * counts_blk.astype(jnp.float32)
    a, bvec = gramian_fused(y_pad, idx_blk, w2, rhs, ridge, yty_arg)
    # [B, R, R] → the solver's lane-batched [R, R, B] layout. This
    # transpose is the one extra HBM round trip the fused path pays
    # (B·R²·4 B — small next to the 2·B·K·R·4 B it removes for K ≳ R;
    # the caller auto-gates on bucket width accordingly).
    a_t = jnp.transpose(a, (1, 2, 0))
    b_t = bvec.T
    bsz = idx_blk.shape[0]
    pad_b = -bsz % _SPD_BLK
    if pad_b:
        a_t = jnp.pad(a_t, ((0, 0), (0, 0), (0, pad_b)))
        b_t = jnp.pad(b_t, ((0, 0), (0, pad_b)))
    x_t = spd_solve_t(a_t, b_t)
    return x_t[:rank, :bsz].T  # [B, rank]


def _solve_side_traced(
    y, buckets, n_rows, rank, implicit, lam, alpha, yty,
    solve_mode="chunked", gather_dtype="f32", mesh=None,
    fused_gather=False,
):
    """Unrolled bucket loop inside a traced program (no per-bucket dispatch).

    ``solve_mode``:

    * ``"chunked"`` — each lax.map step builds one block's normal
      equations AND Cholesky-solves it. Minimal live memory, but the
      sequential depth is (chunks × Cholesky's ~R-step loop).
    * ``"two_phase"`` — the lax.map only builds A/b per chunk (the
      memory-bounded gather stays chunked); ONE batched Cholesky then
      solves the whole bucket, cutting sequential solve depth from
      O(chunks × R) to O(R) per bucket at the cost of materializing
      A [C·B, R, R] (≈1 GB for ML-20M's largest bucket at rank 50).
    * ``"pallas"`` — builds each chunk's normal equations directly in the
      transposed [R, R, B] layout and solves with the fused Cholesky
      kernel (``ops/pallas_kernels.spd_solve_t``); the XLA batched
      Cholesky was ~2/3 of the iteration wall-clock on v5e.

    Under a ``mesh``, the per-chunk SPD systems are embarrassingly
    parallel across solve rows, so the pallas kernel (which does not
    auto-partition under pjit) is wrapped in ``shard_map`` over the
    ``data`` axis: each device Cholesky-solves its local ``[R, R,
    B/n_data]`` block with zero collectives inside the solve. The XLA
    paths (chunked/two_phase) partition automatically and ignore
    ``mesh``.
    """
    x = jnp.zeros((n_rows, rank), dtype=jnp.float32)
    gdt = jnp.bfloat16 if gather_dtype == "bf16" else jnp.float32
    y_g = y.astype(gdt) if y.dtype != gdt else y

    def expand_mask(idx_blk, counts_blk):
        # validity mask rebuilt on device from per-row counts (free: fuses
        # into the gather/einsum; saves a [B, K] host transfer). Dtype
        # follows the gather so the masked product stays bf16 on the
        # reduced-precision path (0/1 are exact in bf16).
        k = idx_blk.shape[-1]
        return (
            jnp.arange(k, dtype=jnp.int32)[None, :] < counts_blk[:, None]
        ).astype(gdt)

    def system(c):
        mask = expand_mask(c[0], c[2])
        if implicit:
            return _system_implicit(
                y_g, yty, c[0], c[1], mask, lam, alpha, rank
            )
        return _system_explicit(y_g, c[0], c[1], mask, lam, rank)

    if solve_mode == "pallas":
        n_pad = (rank + 7) // 8 * 8
        y_pad = jnp.pad(y_g, ((0, 0), (0, n_pad - rank)))
        yty_pad = (
            jnp.pad(yty, ((0, n_pad - rank), (0, n_pad - rank)))
            if implicit
            else None
        )
        eye_t = jnp.eye(n_pad, dtype=jnp.float32)[:, :, None]

        def solve_chunk_pallas(c):
            from .pallas_kernels import _SPD_BLK, spd_solve_t

            idx_blk, val_blk, counts_blk = c
            mask = expand_mask(idx_blk, counts_blk)
            g = y_pad[idx_blk] * mask[..., None]  # [B, K, n_pad]
            if implicit:
                maskf = mask.astype(jnp.float32)
                c1 = (alpha * jnp.abs(val_blk)) * maskf
                pref = (val_blk > 0).astype(jnp.float32) * maskf
                a_t = yty_pad[:, :, None] + jnp.einsum(
                    "bkr,bk,bks->rsb", g, c1.astype(g.dtype), g,
                    preferred_element_type=jnp.float32,
                )
                rhs = (1.0 + c1) * pref
            else:
                a_t = jnp.einsum(
                    "bkr,bks->rsb", g, g,
                    preferred_element_type=jnp.float32,
                )
                rhs = val_blk
            n_u = counts_blk.astype(jnp.float32)  # == mask.sum(axis=1)
            a_t = a_t + (lam * n_u)[None, None, :] * eye_t
            b_t = jnp.einsum(
                "bkr,bk->rb", g, rhs.astype(g.dtype),
                preferred_element_type=jnp.float32,
            )
            bsz = idx_blk.shape[0]
            if mesh is None:
                pad_b = -bsz % _SPD_BLK
                if pad_b:
                    a_t = jnp.pad(a_t, ((0, 0), (0, 0), (0, pad_b)))
                    b_t = jnp.pad(b_t, ((0, 0), (0, pad_b)))
                x_t = spd_solve_t(a_t, b_t)
            else:
                from jax.sharding import PartitionSpec as P

                from ..parallel.collectives import shard_map
                from ..parallel.mesh import DATA_AXIS

                n_data = mesh.shape[DATA_AXIS]
                # each device's local block must itself be a multiple of
                # the kernel's lane block
                pad_b = -bsz % (_SPD_BLK * n_data)
                if pad_b:
                    a_t = jnp.pad(a_t, ((0, 0), (0, 0), (0, pad_b)))
                    b_t = jnp.pad(b_t, ((0, 0), (0, pad_b)))
                x_t = shard_map(
                    spd_solve_t,
                    mesh=mesh,
                    in_specs=(P(None, None, DATA_AXIS), P(None, DATA_AXIS)),
                    out_specs=P(None, DATA_AXIS),
                    check_vma=False,  # pallas body; replication is by spec
                )(a_t, b_t)
            return x_t[:rank, :bsz].T  # [B, rank]

        def solve_chunk_fused(c):
            idx_blk, val_blk, counts_blk = c
            yty_arg = (
                yty_pad if implicit
                else jnp.zeros((n_pad, n_pad), jnp.float32)
            )
            body = functools.partial(
                _fused_chunk_solve, implicit=implicit, rank=rank
            )
            if mesh is None:
                return body(
                    y_pad, yty_arg, lam, alpha, idx_blk, val_blk, counts_blk
                )
            from jax.sharding import PartitionSpec as P

            from ..parallel.collectives import shard_map
            from ..parallel.mesh import DATA_AXIS

            n_data = mesh.shape[DATA_AXIS]
            bsz = idx_blk.shape[0]
            pad_r = -bsz % n_data
            if pad_r:
                idx_blk = jnp.pad(idx_blk, ((0, pad_r), (0, 0)))
                val_blk = jnp.pad(val_blk, ((0, pad_r), (0, 0)))
                counts_blk = jnp.pad(counts_blk, (0, pad_r))
            x_blk = shard_map(
                body,
                mesh=mesh,
                in_specs=(
                    P(), P(), P(), P(), P(DATA_AXIS, None),
                    P(DATA_AXIS, None), P(DATA_AXIS),
                ),
                out_specs=P(DATA_AXIS, None),
                check_vma=False,  # pallas body; replication is by spec
            )(y_pad, yty_arg, lam, alpha, idx_blk, val_blk, counts_blk)
            return x_blk[:bsz]

    for rows, idx, val, counts in buckets:
        if idx.dtype != jnp.int32:
            idx = idx.astype(jnp.int32)  # uint16 transfer packing
        if solve_mode == "pallas":
            # fused gather+Gramian only pays for itself when the removed
            # [B, K, R] round trip outweighs its [B, R, R] transpose —
            # i.e. width >= rank; narrow buckets keep the einsum build
            fn = (
                solve_chunk_fused
                if fused_gather and idx.shape[-1] >= rank
                else solve_chunk_pallas
            )
            solved = jax.lax.map(fn, (idx, val, counts))
        elif solve_mode == "two_phase":
            a, b = jax.lax.map(system, (idx, val, counts))
            solved = _cho_solve(
                a.reshape(-1, rank, rank), b.reshape(-1, rank)
            )
        else:
            solved = jax.lax.map(lambda c: _cho_solve(*system(c)),
                                 (idx, val, counts))
        x = x.at[rows.reshape(-1)].set(solved.reshape(-1, rank), mode="drop")
    return x


def _als_iteration_body(
    user_buckets, item_buckets, y, lam, alpha,
    rank, implicit, n_users, n_items, solve_mode="chunked",
    gather_dtype="f32", mesh=None, fused_gather=False,
):
    """One full ALS iteration (user solve + item solve, all buckets) as a
    single device program — one dispatch per iteration. ``lam``/``alpha``
    are dynamic so hyperparameter sweeps reuse the compilation.

    (A whole-run ``fori_loop`` fusion compiles pathologically on some
    backends; per-iteration fusion keeps dispatch count at
    ``iterations`` while staying cheap to compile.)"""
    yty = (
        jnp.einsum("nr,ns->rs", y, y, preferred_element_type=jnp.float32)
        if implicit
        else None
    )
    x = _solve_side_traced(
        y, user_buckets, n_users, rank, implicit, lam, alpha, yty,
        solve_mode=solve_mode, gather_dtype=gather_dtype, mesh=mesh,
        fused_gather=fused_gather,
    )
    xtx = (
        jnp.einsum("nr,ns->rs", x, x, preferred_element_type=jnp.float32)
        if implicit
        else None
    )
    y2 = _solve_side_traced(
        x, item_buckets, n_items, rank, implicit, lam, alpha, xtx,
        solve_mode=solve_mode, gather_dtype=gather_dtype, mesh=mesh,
        fused_gather=fused_gather,
    )
    return x, y2


def _als_half_body(
    y, buckets, lam, alpha,
    rank, implicit, n_rows, solve_mode="chunked",
    gather_dtype="f32", mesh=None, fused_gather=False,
):
    """One HALF iteration (solve one side from the opposite factors) as its
    own device program. The training loop uses this for the first executed
    iteration only: a program that needs just one side's buckets can start
    the moment that side's host→device transfer lands, so the other side's
    transfer overlaps the first solve instead of gating it — the staging
    overlap of VERDICT r3 item 4. Later iterations keep the fused
    whole-iteration program (one dispatch each)."""
    yty = (
        jnp.einsum("nr,ns->rs", y, y, preferred_element_type=jnp.float32)
        if implicit
        else None
    )
    return _solve_side_traced(
        y, buckets, n_rows, rank, implicit, lam, alpha, yty,
        solve_mode=solve_mode, gather_dtype=gather_dtype, mesh=mesh,
        fused_gather=fused_gather,
    )


_HALF_STATICS = (
    "rank", "implicit", "n_rows", "solve_mode",
    "gather_dtype", "mesh", "fused_gather",
)

_als_half = functools.partial(
    jax.jit, static_argnames=_HALF_STATICS
)(_als_half_body)


@functools.lru_cache(maxsize=32)
def _als_half_sharded(out_sharding):
    return jax.jit(
        _als_half_body,
        static_argnames=_HALF_STATICS,
        out_shardings=out_sharding,
    )


# ``mesh`` is static: jax.sharding.Mesh is hashable, and the traced program
# embeds per-device pallas blocks via shard_map when it is set.
_als_iteration = functools.partial(
    jax.jit,
    static_argnames=(
        "rank", "implicit", "n_users", "n_items", "solve_mode",
        "gather_dtype", "mesh", "fused_gather",
    ),
)(_als_iteration_body)


@functools.lru_cache(maxsize=32)
def _als_iteration_sharded(out_sharding):
    """Jit of the iteration with factor-table output shardings pinned (both
    tables get ``out_sharding``); cached per sharding so sweeps reuse the
    compilation."""
    return jax.jit(
        _als_iteration_body,
        static_argnames=(
            "rank", "implicit", "n_users", "n_items", "solve_mode",
            "gather_dtype", "mesh", "fused_gather",
        ),
        out_shardings=(out_sharding, out_sharding),
    )


def als_train(
    by_user,
    by_item,
    cfg: ALSConfig,
    mesh=None,
    factor_sharding: str = "replicated",
    checkpoint=None,
    checkpoint_every: int = 0,
    profile: Optional[dict] = None,
) -> ALSFactors:
    """Alternating solves: items → users → items … for ``cfg.iterations``.

    ``by_user`` holds ratings grouped by user (solving users), ``by_item``
    the transpose (solving items); either :class:`BucketedMatrix` (host) or
    :class:`StagedMatrix` (already on device). Mirrors MLlib's iteration
    order: item factors are initialized and users are solved first. Bucket
    tensors are staged to device once; the full run is one fused device
    program.

    Distributed training: pass a ``jax.sharding.Mesh`` with a ``data`` axis
    (and a ``model`` axis when ``factor_sharding="model"``). Solve rows ride
    the ``data`` axis (the analogue of the reference's RDD partitions);
    factor tables are either replicated (default — XLA all-gathers fresh
    factors each half-iteration over ICI) or row-sharded over ``model``
    (MLlib's ALS block partitioning analogue: gathers become cross-shard
    collectives, for tables too big to replicate). The collective schedule
    is derived by XLA from these annotations, not hand-written.

    ``profile`` (optional dict) receives a perf breakdown: ``stage_s``
    (host→device transfer), ``iteration_s`` (per-iteration wall-clock,
    synchronized), and ``flops_per_iteration`` (padded-shape estimate for
    MFU accounting). Per-iteration sync costs nothing extra: each
    iteration is one device program with a data dependency on the last.
    """
    import time as _time

    if cfg.iterations < 1:
        raise ValueError(f"ALS iterations must be >= 1, got {cfg.iterations}")
    if cfg.solve_mode not in ("auto", "chunked", "two_phase", "pallas"):
        raise ValueError(
            f"solve_mode must be 'auto', 'chunked', 'two_phase' or "
            f"'pallas', got {cfg.solve_mode!r}"
        )
    if cfg.gather_dtype not in ("f32", "bf16"):
        raise ValueError(
            f"gather_dtype must be 'f32' or 'bf16', got {cfg.gather_dtype!r}"
        )
    staged_inputs = not (
        isinstance(by_user, BucketedMatrix)
        and isinstance(by_item, BucketedMatrix)
    )
    levers = cfg.resolve_levers(staged_inputs=staged_inputs)
    solve_mode = levers["solve_mode"]
    # The pallas solve kernel has bounded VMEM scratch (rank padded to a
    # multiple of 8, n²·128·4 bytes) — "auto" selects around that limit;
    # an explicit "pallas" beyond it must fail loudly, not die in
    # Mosaic's allocator. Under a mesh the kernel runs per-device inside
    # shard_map over the data axis (see _solve_side_traced), so
    # distributed training keeps the fused-Cholesky iteration win.
    if cfg.solve_mode == "pallas" and cfg.rank > 80:
        raise ValueError(
            f"solve_mode='pallas' supports rank <= 80 (VMEM scratch "
            f"bound), got rank={cfg.rank}; use 'auto' or 'chunked'"
        )
    if cfg.fused_gather and solve_mode != "pallas":
        # only an EXPLICIT True can conflict (the None default resolves
        # with the solve mode); a silently ignored flag would corrupt
        # the hardware A/B
        raise ValueError(
            "fused_gather=True requires solve_mode to resolve to 'pallas' "
            f"(resolved to {solve_mode!r}); pass solve_mode='pallas' "
            "explicitly off-TPU"
        )
    fused_gather = levers["fused_gather"]
    sort_gather = levers["sort_gather"]
    rank = cfg.rank

    iteration = _als_iteration
    half = _als_half
    row_sharding = None
    row_multiple = 1
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

        if factor_sharding == "model":
            tbl_spec = NamedSharding(mesh, P(MODEL_AXIS))
        elif factor_sharding == "replicated":
            tbl_spec = NamedSharding(mesh, P())
        else:
            raise ValueError(
                f"factor_sharding must be 'replicated' or 'model', "
                f"got {factor_sharding!r}"
            )
        row_sharding = NamedSharding(mesh, P(None, DATA_AXIS))
        row_multiple = mesh.shape[DATA_AXIS]
        iteration = _als_iteration_sharded(tbl_spec)
        half = _als_half_sharded(tbl_spec)

    t_stage = _time.monotonic()
    if cfg.sort_gather_indices and staged_inputs:
        # already-staged tensors cannot be reordered host-side; only an
        # EXPLICIT True can conflict (the None default resolves to False
        # for staged inputs) and silently ignoring it would corrupt an
        # A/B measurement
        raise ValueError(
            "sort_gather_indices=True requires BucketedMatrix inputs "
            "(sort before staging: sort_bucket_indices(bucketize(...)))"
        )
    if sort_gather:
        # gather-locality pass (host, pre-staging); see sort_bucket_indices
        by_user = sort_bucket_indices(by_user)
        by_item = sort_bucket_indices(by_item)
    if isinstance(by_user, BucketedMatrix):
        by_user = stage(by_user, row_sharding, row_multiple)
    if isinstance(by_item, BucketedMatrix):
        by_item = stage(by_item, row_sharding, row_multiple)
    if profile is not None:
        profile["stage_s"] = _time.monotonic() - t_stage
        # RESOLVED lever flags — what this run actually executed, not
        # what the config requested (tri-state defaults resolve here);
        # the bench and perf ledger record these (docs/performance.md)
        profile["solve_mode"] = solve_mode
        profile["gather_dtype"] = cfg.gather_dtype
        profile["sort_gather"] = sort_gather
        profile["fused_gather"] = fused_gather
        profile["flops_per_iteration"] = estimate_iteration_flops(
            by_user, by_item, rank, cfg.implicit_prefs
        )
        profile["hbm_bytes_per_iteration"] = estimate_iteration_hbm_bytes(
            by_user, by_item, rank, cfg.gather_dtype,
            fused_gather=fused_gather,
        )
        profile["bucket_shapes"] = {
            "by_user": [
                [int(np.prod(b.rows.shape)), b.idx.shape[-1]]
                for b in by_user.buckets
            ],
            "by_item": [
                [int(np.prod(b.rows.shape)), b.idx.shape[-1]]
                for b in by_item.buckets
            ],
        }
        profile.setdefault("iteration_s", [])
    y = init_factors(by_item.n_rows, rank, cfg.seed)  # item factors
    if mesh is not None:
        y = jax.device_put(y, tbl_spec)
    ub, ib = _bucket_tensors(by_user), _bucket_tensors(by_item)
    lam, alpha = jnp.float32(cfg.lambda_), jnp.float32(cfg.alpha)
    x = None

    # step-level resume (SURVEY §5: strictly better than the reference's
    # run-to-completion-or-die ALS). A checkpoint is only resumed when its
    # FULL training configuration matches — rank/shape alone is not identity
    # (two algorithm blocks can share shapes but differ in lambda/seed).
    ck_meta = {
        "rank": rank,
        "lambda": float(cfg.lambda_),
        "alpha": float(cfg.alpha),
        "implicit": bool(cfg.implicit_prefs),
        "seed": int(cfg.seed),
        "nnz": int(by_user.nnz),
    }
    start = 0
    if checkpoint is not None:
        # Scan steps newest-first for the first VALID one: config identity
        # matches, shapes match, and step <= cfg.iterations (a stale
        # higher-step checkpoint from a longer past run must not block
        # resume from an earlier in-range step). An unreadable/corrupt
        # checkpoint is treated as absent, not fatal.
        for step in reversed(checkpoint.all_steps()):
            if step > cfg.iterations:
                continue
            try:
                step, tree, meta = checkpoint.restore(
                    step, like={"x": 0, "y": 0}
                )
            except Exception:
                continue  # torn/corrupt save — keep scanning older steps
            if (
                all(meta.get(k) == v for k, v in ck_meta.items())
                and tuple(tree["y"].shape) == (by_item.n_rows, rank)
                and tuple(tree["x"].shape) == (by_user.n_rows, rank)
            ):
                x = jnp.asarray(tree["x"])
                y = jnp.asarray(tree["y"])
                if mesh is not None:
                    x, y = (
                        jax.device_put(x, tbl_spec),
                        jax.device_put(y, tbl_spec),
                    )
                start = step
                break

    common = dict(
        rank=rank,
        implicit=cfg.implicit_prefs,
        solve_mode=solve_mode,
        gather_dtype=cfg.gather_dtype,
        mesh=mesh if solve_mode == "pallas" else None,
        fused_gather=fused_gather,
    )
    # jit boundary telemetry (docs/observability.md#profiling): a solve
    # call that compiles is counted (and, past the first, counted as a
    # retrace) — the signal that distinguishes "the solver is slow" from
    # "the solver keeps recompiling"
    from ..obs.profile import default_telemetry

    _telemetry = default_telemetry()
    for i in range(start, cfg.iterations):
        t_iter = _time.monotonic()
        if i == start:
            # first executed iteration as two half programs: the user
            # solve needs only the user-side buckets, so it starts as
            # soon as they land while the item-side transfer is still in
            # flight (same math — the fused body is these two calls)
            x = _telemetry.call(
                "als_half", half, y, ub, lam, alpha,
                n_rows=by_user.n_rows, **common,
            )
            y = _telemetry.call(
                "als_half", half, x, ib, lam, alpha,
                n_rows=by_item.n_rows, **common,
            )
        else:
            x, y = _telemetry.call(
                "als_iteration", iteration,
                ub, ib, y, lam, alpha,
                n_users=by_user.n_rows,
                n_items=by_item.n_rows,
                **common,
            )
        if profile is not None:
            jax.block_until_ready((x, y))
            profile["iteration_s"].append(_time.monotonic() - t_iter)
        done = i + 1
        if (
            checkpoint is not None
            and checkpoint_every > 0
            and (done % checkpoint_every == 0 or done == cfg.iterations)
        ):
            checkpoint.save(
                done,
                {"x": np.asarray(x), "y": np.asarray(y)},
                {**ck_meta, "iteration": done},
            )
    return ALSFactors(user_factors=x, item_factors=y, rank=rank)


def estimate_iteration_flops(
    by_user: StagedMatrix, by_item: StagedMatrix, rank: int, implicit: bool
) -> float:
    """Padded-shape FLOP estimate for ONE full ALS iteration (both sides) —
    what the device actually executes, for MFU accounting. Per padded row of
    width K: Gramian einsum 2·K·R², rhs einsum 2·K·R, Cholesky ≈ R³/3,
    triangular solves ≈ 2·R²."""
    total = 0.0
    for side in (by_user, by_item):
        for b in side.buckets:
            rows = float(np.prod(b.rows.shape))  # padded rows incl. chunks
            k = float(b.idx.shape[-1])
            total += rows * (
                2.0 * k * rank * rank
                + 2.0 * k * rank
                + rank**3 / 3.0
                + 2.0 * rank * rank
            )
        if implicit:
            total += 2.0 * side.n_cols * rank * rank  # YᵀY
    return total


def estimate_iteration_hbm_bytes(
    by_user: StagedMatrix, by_item: StagedMatrix, rank: int,
    gather_dtype: str = "f32",
    fused_gather: bool = False,
) -> float:
    """Padded-shape HBM-traffic estimate for one full iteration — the ALS
    solve is gather-bound, so bandwidth utilization (not MFU) is the
    honest efficiency number.

    Einsum-built path, per padded row of width K, per side: the factor
    gather reads K·R elements (the dominant term — counted at the gather
    dtype's width, 2 B for bf16), idx/val/counts stream in once, and the
    solved row writes back R floats. Real gathers touch whole (8,128)
    tiles, so treat this as a lower bound on true traffic.

    Fused path (``fused_gather=True``, buckets with K >= rank — narrower
    buckets keep the einsum build, mirroring ``_solve_side_traced``'s
    auto-gate): each rating's factor row moves as ONE lane-aligned
    1×128-lane f32 DMA — 512 B at bench ranks, REGARDLESS of
    ``gather_dtype`` (Mosaic cannot slice a half-width bf16 sublane, so
    the kernel upcasts at entry; ``ops/pallas_kernels.gramian_fused``) —
    plus the [B, R, R] systems written once and re-read through the
    transposed-layout round trip the solver needs. bf16 therefore buys
    bytes only on the einsum path; the fused path's win is removing the
    [B, K, R] intermediate, not narrowing the rows."""
    elt = 2.0 if gather_dtype == "bf16" else 4.0
    lane_pad = float(-(-int(rank) // 128) * 128)  # 1×128-lane DMA floor
    total = 0.0
    for side in (by_user, by_item):
        for b in side.buckets:
            rows = float(np.prod(b.rows.shape))
            k = float(b.idx.shape[-1])
            idx_b = b.idx.dtype.itemsize
            if fused_gather and k >= rank:
                per_row = (
                    k * lane_pad * 4.0  # per-rating aligned row DMAs (f32)
                    + k * (idx_b + 4.0)  # idx + val stream
                    + 4.0  # per-row counts read
                    + 3.0 * rank * rank * 4.0  # A write + transpose trip
                    + 2.0 * rank * 4.0  # rhs vector + solution write
                )
            else:
                per_row = (
                    k * rank * elt  # gathered opposite factors
                    + k * (idx_b + 4.0)  # idx + val stream
                    + 4.0  # per-row counts read
                    + rank * 4.0  # solution write
                )
            total += rows * per_row
    return total


def als_train_coo(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    cfg: ALSConfig,
    mesh=None,
    factor_sharding: str = "replicated",
    checkpoint=None,
    checkpoint_every: int = 0,
) -> ALSFactors:
    """Convenience: COO triplets → bucketized both ways → train."""
    by_user = bucketize(
        users, items, ratings, n_users, n_items, pad_to_blocks=True
    )
    by_item = bucketize(
        items, users, ratings, n_items, n_users, pad_to_blocks=True
    )
    return als_train(
        by_user, by_item, cfg, mesh=mesh, factor_sharding=factor_sharding,
        checkpoint=checkpoint, checkpoint_every=checkpoint_every,
    )


@functools.partial(jax.jit, static_argnames=())
def predict_pairs(
    user_factors: jax.Array, item_factors: jax.Array, u: jax.Array, i: jax.Array
) -> jax.Array:
    """r̂ for (user, item) pairs — the RMSE-evaluation path."""
    return jnp.sum(user_factors[u] * item_factors[i], axis=-1)


def rmse(
    factors: ALSFactors, users: np.ndarray, items: np.ndarray, ratings: np.ndarray
) -> float:
    preds = predict_pairs(
        factors.user_factors,
        factors.item_factors,
        jnp.asarray(users, dtype=jnp.int32),
        jnp.asarray(items, dtype=jnp.int32),
    )
    err = preds - jnp.asarray(ratings, dtype=jnp.float32)
    return float(jnp.sqrt(jnp.mean(err * err)))
