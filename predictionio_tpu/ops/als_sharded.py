"""ALX-style sharded ALS: both factor tables sharded over a named mesh axis.

The pod-scale data plane the ROADMAP calls "the single biggest unlock for
'fast as the hardware allows'": *ALX: Large Scale Matrix Factorization on
TPUs* (arXiv:2112.02194, PAPERS.md) shards BOTH factor matrices across
devices, balances density-bucketed batches per shard, and overlaps
off-shard factor gathers with solves. This module is that trainer, built
on ``shard_map`` so the collective schedule is explicit:

1. **Row → shard assignment** (:func:`assign_rows_balanced`): every row's
   solve cost is a pure function of its padded bucket width (the degree
   buckets of ``ops/als.py``), so rows are dealt to shards greedily
   least-loaded per width class, widest first — a deliberately skewed
   degree histogram still splits within a small FLOP-imbalance bound
   (pinned in tests/test_sharded_train.py).
2. **Per-shard bucketization**: each shard bucketizes ITS rows
   independently with the right-sized ``_alloc_block`` allocation, so no
   shard pays another shard's padding; shards are then padded to a common
   per-width envelope (which the balancing keeps tight) purely so the
   slabs stack into one ``[S, C, B, K]`` array ``shard_map`` can split.
3. **Sharded factor layout**: the table for a side with ``n`` rows lives
   as ``[S * cap, R]`` sharded ``P(SHARD_AXIS)`` — shard ``s`` owns local
   slots ``[s*cap, (s+1)*cap)``; rating column indices are pre-translated
   into this permuted space on the host, so the device program never
   needs the global permutation.
4. **Off-shard gathers overlapped with solves**: inside the mapped body,
   one tiled ``all_gather`` fetches the opposite table's row shards; each
   bucket's slab then reads its referenced rows through the shared
   ragged/deduplicated gather (``quant.ragged_gather`` — each unique row
   touched once, duplicates replayed via the inverse map; bit-identical
   to the dense ``y_full[idx]`` it replaced), issued — in program order,
   dataflow-independent — BEFORE the previous bucket's solves, a
   software pipeline XLA's latency-hiding scheduler can overlap on TPU.
   (Extending the ragged fetch across shards — skipping the dense
   all-gather entirely at shard counts where replicating the table per
   device no longer fits — remains hardware-day headroom in
   docs/distributed_training.md.)
5. **Implicit mode** builds YᵀY as a ``psum`` of per-shard Gramians — the
   collective the ``spmd-*`` lint family pins this file as the clean
   exemplar for.

Equivalence contract (the CI-runnable proof, on the 8-virtual-CPU-device
test mesh): factors at 1/2/4/8 shards match the single-device trainer
within the PR-12 reassociation tolerances (rtol 1e-3 / atol 1e-4, holdout
RMSE 1e-3) — sharding changes accumulation ORDER (per-shard index sorting
happens in permuted id space), never the per-row math. The multi-host
``jax.distributed`` drive is scripted for hardware day
(docs/hardware_day.md#multi-host-train).
"""

from __future__ import annotations

import dataclasses
import functools
import heapq
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.collectives import shard_map
from ..quant.ragged import ragged_gather
from ..parallel.mesh import DATA_AXIS, MeshConfig, create_mesh
from .als import (
    ALSConfig,
    ALSFactors,
    DEFAULT_BUCKET_WIDTHS,
    _alloc_block,
    _cho_solve,
    _idx_dtype,
    _system_explicit_g,
    _system_implicit_g,
    als_train,
    bucketize,
    init_factors,
    sort_bucket_indices,
)

__all__ = [
    "SHARD_AXIS",
    "SHARDS_ENV",
    "assign_rows_balanced",
    "als_train_sharded",
    "plan_side",
    "resolve_shards",
    "row_solve_flops",
]

#: Solve rows ride the mesh ``data`` axis — the same axis name the rest of
#: the parallel plane uses, so a hybrid (DCN x ICI) mesh slots in directly.
SHARD_AXIS = DATA_AXIS

#: Env override for the ``shards`` tri-state (``pio train --shards`` sets
#: it; docs/cli.md#environment-variables).
SHARDS_ENV = "PIO_TRAIN_SHARDS"


def resolve_shards(
    shards: Optional[int] = None,
    env: Optional[Dict[str, str]] = None,
) -> int:
    """The CONCRETE shard count a train run will execute — the
    ``ALSAlgorithmParams.shards`` tri-state resolved per the PR-12 lever
    discipline: an explicit value wins, else :data:`SHARDS_ENV` (what
    ``pio train --shards N`` sets), else 1 — the single-device trainer,
    byte-identical config resolution to today's path. Resolution never
    silently clamps: a count the device pool cannot satisfy fails loudly
    in :func:`als_train_sharded`, not here."""
    if shards is not None:
        n = int(shards)
        if n < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        return n
    e = env if env is not None else os.environ
    raw = e.get(SHARDS_ENV)
    if raw:
        try:
            n = int(raw)
        except ValueError:
            raise ValueError(f"{SHARDS_ENV} must be an integer, got {raw!r}")
        if n < 1:
            raise ValueError(f"{SHARDS_ENV} must be >= 1, got {raw!r}")
        return n
    return 1


def row_solve_flops(width: int, rank: int) -> float:
    """Padded solve cost of ONE bucket row of width K — the same
    accounting as ``ops.als.estimate_iteration_flops`` (Gramian + rhs
    einsums, Cholesky, triangular solves), which makes it the right
    balancing weight: what the device actually executes per row."""
    k = float(width)
    r = float(rank)
    return k * (2.0 * r * r + 2.0 * r) + r**3 / 3.0 + 2.0 * r * r


def _padded_widths(
    degrees: np.ndarray, widths: Sequence[int]
) -> np.ndarray:
    """Each row's padded bucket width (rows above the largest width
    truncate to it, mirroring ``bucketize``)."""
    ws = np.asarray(sorted(widths), dtype=np.int64)
    capped = np.minimum(degrees.astype(np.int64), ws[-1])
    return ws[np.searchsorted(ws, capped, side="left")]


def assign_rows_balanced(
    degrees: np.ndarray,
    shards: int,
    bucket_widths: Sequence[int] = DEFAULT_BUCKET_WIDTHS,
    rank: int = 10,
) -> np.ndarray:
    """Deal rows to shards balancing per-shard solve FLOPs.

    Every row in one width class costs the same, so balance reduces to
    dealing each class's rows (widest/heaviest class first) to the
    currently least-loaded shard — deterministic (ties break on shard
    index, rows visit in ascending id order) and within one row-cost of
    perfect per class. Zero-degree rows carry no solve cost and are dealt
    last to the emptiest shards so local row counts stay even (they size
    the sharded factor table's per-shard ``cap``).

    Returns the ``[n_rows]`` int32 shard assignment.
    """
    n = len(degrees)
    assign = np.zeros(n, dtype=np.int32)
    if shards <= 1:
        return assign
    widths = _padded_widths(np.asarray(degrees), bucket_widths)
    load = [(0.0, s) for s in range(shards)]  # (flops, shard) min-heap
    heapq.heapify(load)
    rated = np.nonzero(np.asarray(degrees) > 0)[0]
    # widest class first: the heaviest rows set the landscape the lighter
    # classes then level out
    order = np.lexsort((rated, -widths[rated]))
    for row in rated[order]:
        cost = row_solve_flops(int(widths[row]), rank)
        flops, s = heapq.heappop(load)
        assign[row] = s
        heapq.heappush(load, (flops + cost, s))
    # zero-degree rows: even out the LOCAL ROW COUNTS (table cap), not the
    # flops — they never solve
    counts = np.bincount(assign[rated], minlength=shards)
    count_heap = [(int(counts[s]), s) for s in range(shards)]
    heapq.heapify(count_heap)
    for row in np.nonzero(np.asarray(degrees) <= 0)[0]:
        c, s = heapq.heappop(count_heap)
        assign[row] = s
        heapq.heappush(count_heap, (c + 1, s))
    return assign


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """One side's row → (shard, local slot) layout.

    The permuted factor table is ``[shards * cap, R]`` sharded over
    :data:`SHARD_AXIS`; global row ``r`` lives at flat index
    ``assign[r] * cap + slot[r]``. Slots beyond a shard's real row count
    are zero padding (never referenced, never solved)."""

    shards: int
    assign: np.ndarray  # [n] -> owning shard
    slot: np.ndarray  # [n] -> local slot within the shard
    cap: int  # local rows per shard (max over shards, >= 1)
    per_shard_flops: Tuple[float, ...]  # balancing evidence

    @property
    def flop_imbalance(self) -> float:
        """max/mean per-shard solve FLOPs (1.0 = perfect balance)."""
        mean = sum(self.per_shard_flops) / max(1, len(self.per_shard_flops))
        if mean <= 0:
            return 1.0
        return max(self.per_shard_flops) / mean

    def flat_index(self, rows: np.ndarray) -> np.ndarray:
        return (
            self.assign[rows].astype(np.int64) * self.cap
            + self.slot[rows].astype(np.int64)
        )


def plan_side(
    degrees: np.ndarray,
    shards: int,
    bucket_widths: Sequence[int] = DEFAULT_BUCKET_WIDTHS,
    rank: int = 10,
) -> ShardPlan:
    """Assignment + local slots + per-shard FLOP stats for one side."""
    degrees = np.asarray(degrees)
    n = len(degrees)
    assign = assign_rows_balanced(degrees, shards, bucket_widths, rank)
    # local slot = rank of the row within its shard, ascending global id
    # (stable sort keeps the order deterministic)
    order = np.argsort(assign, kind="stable")
    counts = np.bincount(assign, minlength=shards)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot = np.zeros(n, dtype=np.int32)
    slot[order] = (
        np.arange(n, dtype=np.int64) - np.repeat(starts, counts)
    ).astype(np.int32)
    widths = _padded_widths(degrees, bucket_widths)
    flops = np.array(
        [row_solve_flops(int(w), rank) for w in np.sort(np.unique(widths))]
    )
    per_shard = []
    uniq = np.sort(np.unique(widths))
    rated = degrees > 0
    for s in range(shards):
        sel = rated & (assign == s)
        total = 0.0
        for wi, w in enumerate(uniq):
            total += float(flops[wi]) * int(np.sum(widths[sel] == w))
        per_shard.append(total)
    cap = max(1, int(counts.max()))
    return ShardPlan(
        shards=shards,
        assign=assign,
        slot=slot,
        cap=cap,
        per_shard_flops=tuple(per_shard),
    )


def _build_side(
    row_ids: np.ndarray,
    col_ids: np.ndarray,
    vals: np.ndarray,
    row_plan: ShardPlan,
    col_plan: ShardPlan,
    bucket_widths: Sequence[int],
    sort: bool,
):
    """Per-shard right-sized buckets, stacked into shard-leading slabs.

    Each shard bucketizes its OWN rows (local slot ids, opposite-side
    column ids pre-translated into the permuted ``[S * cap_col]`` space)
    with ``pad_to_blocks=True`` — the PR-12 right-sized allocation, so a
    shard's slab envelope follows ITS row histogram. Shards then pad to
    the max envelope per width (sentinel rows: ``rows == cap`` dropped by
    the scatter, counts 0) purely to stack; the FLOP balancing is what
    keeps that common envelope tight.

    Returns ``(slabs, padded_rows)`` — slabs is a tuple of
    ``(rows [S,C,B], idx [S,C,B,K], val, counts)`` numpy stacks in width
    order; ``padded_rows`` maps width → total padded rows (profile/FLOP
    accounting).
    """
    shards = row_plan.shards
    n_cols_perm = col_plan.shards * col_plan.cap
    row_ids = np.asarray(row_ids)
    perm_cols = col_plan.flat_index(np.asarray(col_ids)).astype(np.int32)
    local_rows = row_plan.slot[row_ids]
    shard_of = row_plan.assign[row_ids]
    per_shard: List[Dict[int, object]] = []
    for s in range(shards):
        sel = shard_of == s
        bm = bucketize(
            local_rows[sel],
            perm_cols[sel],
            np.asarray(vals)[sel],
            n_rows=row_plan.cap,
            n_cols=n_cols_perm,
            bucket_widths=bucket_widths,
            pad_to_blocks=True,
        )
        if sort:
            # gather locality in the PERMUTED id space (adjacent permuted
            # ids are adjacent rows of the gathered table)
            bm = sort_bucket_indices(bm)
        per_shard.append({b.width: b for b in bm.buckets})
    all_widths = sorted({w for shard in per_shard for w in shard})
    idx_dtype = _idx_dtype(n_cols_perm)
    slabs = []
    padded_rows: Dict[int, int] = {}
    for w in all_widths:
        real_max = max(
            (
                int((shard[w].counts > 0).sum())
                for shard in per_shard
                if w in shard
            ),
            default=0,
        )
        alloc_max = max(
            (shard[w].rows.shape[0] for shard in per_shard if w in shard),
            default=0,
        )
        block = _alloc_block(w, real_max)
        b_rows = max(block, -(-alloc_max // block) * block)
        n_chunks = b_rows // block
        rows = np.full((shards, b_rows), row_plan.cap, dtype=np.int32)
        idx = np.zeros((shards, b_rows, w), dtype=idx_dtype)
        val = np.zeros((shards, b_rows, w), dtype=np.float32)
        counts = np.zeros((shards, b_rows), dtype=np.int32)
        for s, shard in enumerate(per_shard):
            b = shard.get(w)
            if b is None:
                continue
            m = b.rows.shape[0]
            rows[s, :m] = b.rows
            idx[s, :m] = b.idx.astype(idx_dtype)
            val[s, :m] = b.val
            counts[s, :m] = b.counts
        slabs.append(
            (
                rows.reshape(shards, n_chunks, block),
                idx.reshape(shards, n_chunks, block, w),
                val.reshape(shards, n_chunks, block, w),
                counts.reshape(shards, n_chunks, block),
            )
        )
        padded_rows[w] = shards * b_rows
    return tuple(slabs), padded_rows


def _half_sharded_body(
    y_table,
    slabs,
    lam,
    alpha,
    *,
    mesh,
    rank,
    implicit,
    gather_dtype,
    cap_x,
):
    """One sharded half-iteration: solve every local row of one side from
    the sharded opposite table. ``y_table`` is ``[S * cap_y, R]`` sharded
    ``P(SHARD_AXIS)``; ``slabs`` are the shard-leading bucket stacks;
    returns the solved ``[S * cap_x, R]`` table, same sharding."""
    gdt = jnp.bfloat16 if gather_dtype == "bf16" else jnp.float32

    def _shard_body(y_local, local_slabs, lam_s, alpha_s):
        # Off-shard factor fetch: one tiled all-gather of the opposite
        # table's row shards; per-bucket slabs then gather raggedly from
        # it. (Skipping the all-gather itself — fetching only referenced
        # rows ACROSS shards at counts where replicating the table no
        # longer fits — stays docs/distributed_training.md#headroom.)
        y_full = jax.lax.all_gather(y_local, SHARD_AXIS, axis=0, tiled=True)
        y_g = y_full.astype(gdt) if y_full.dtype != gdt else y_full
        if implicit:
            # YᵀY over the whole table as a psum of per-shard Gramians —
            # padding slots are zero rows, so they contribute nothing
            local_yty = jnp.einsum(
                "nr,ns->rs", y_local, y_local,
                preferred_element_type=jnp.float32,
            )
            yty = jax.lax.psum(local_yty, SHARD_AXIS)
        else:
            yty = None

        def gather_chunk(idx_blk, counts_blk):
            idx_blk = idx_blk.astype(jnp.int32)  # uint16 transfer packing
            k = idx_blk.shape[-1]
            mask = (
                jnp.arange(k, dtype=jnp.int32)[None, :]
                < counts_blk[:, None]
            ).astype(gdt)
            # ragged/deduplicated slab fetch (quant.ragged_gather): a
            # solve block's columns repeat hot counterpart rows, and the
            # padding slots all point at slot 0 — each unique row is
            # read once instead of once per reference
            return ragged_gather(y_g, idx_blk) * mask[..., None], mask

        def solve_from_g(g, mask, val_blk):
            if implicit:
                a, b = _system_implicit_g(
                    g, yty, val_blk, mask, lam_s, alpha_s, rank
                )
            else:
                a, b = _system_explicit_g(g, val_blk, mask, lam_s, rank)
            return _cho_solve(a, b)

        def solve_chunk(c):
            idx_blk, val_blk, counts_blk = c
            g, mask = gather_chunk(idx_blk, counts_blk)
            return solve_from_g(g, mask, val_blk)

        # drop the leading shard dim (1 per device under shard_map)
        buckets = [tuple(t[0] for t in slab) for slab in local_slabs]
        x = jnp.zeros((cap_x, rank), dtype=jnp.float32)
        # Software pipeline: bucket b+1's first off-shard gather is issued
        # BEFORE bucket b's solves in program order and depends on none of
        # them, so the scheduler can overlap the gather DMA with the
        # previous bucket's solve chain (the ALX overlap, expressed as
        # dataflow).
        pre = None
        if buckets:
            _, idx0, _, counts0 = buckets[0]
            pre = gather_chunk(idx0[0], counts0[0])
        for bi, (rows, idx, val, counts) in enumerate(buckets):
            nxt = None
            if bi + 1 < len(buckets):
                _, idx_n, _, counts_n = buckets[bi + 1]
                nxt = gather_chunk(idx_n[0], counts_n[0])
            g, mask = pre
            first = solve_from_g(g, mask, val[0])  # prefetched chunk 0
            if idx.shape[0] > 1:
                rest = jax.lax.map(
                    solve_chunk, (idx[1:], val[1:], counts[1:])
                )
                solved = jnp.concatenate([first[None], rest], axis=0)
            else:
                solved = first[None]
            # sentinel rows carry cap_x (out of range) -> dropped
            x = x.at[rows.reshape(-1)].set(
                solved.reshape(-1, rank), mode="drop"
            )
            pre = nxt
        return x

    return shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
        out_specs=P(SHARD_AXIS),
        # the body all-gathers + psums; replication is by spec, which the
        # static VMA check cannot prove through all_gather (the
        # all_gather_rows precedent in parallel/collectives.py)
        check_vma=False,
    )(y_table, slabs, lam, alpha)


_half_sharded = functools.partial(
    jax.jit,
    static_argnames=("mesh", "rank", "implicit", "gather_dtype", "cap_x"),
)(_half_sharded_body)


def resolve_sharded_levers(cfg: ALSConfig) -> dict:
    """Lever resolution for the sharded data plane (the PR-12 "record
    resolved, not requested" discipline). The sharded trainer builds
    normal equations with the einsum path and solves with the batched
    Cholesky per shard — ``solve_mode`` must be ``auto``/``chunked`` and
    ``fused_gather`` must not be forced on; composing the fused Pallas
    build inside the mapped body is hardware-day headroom
    (docs/distributed_training.md#headroom). A silently ignored flag
    would corrupt the hardware A/B, so explicit conflicts fail loudly."""
    if cfg.solve_mode not in ("auto", "chunked"):
        raise ValueError(
            "sharded training solves 'chunked' (einsum build + batched "
            f"Cholesky per shard); solve_mode={cfg.solve_mode!r} is not "
            "supported with shards > 1 — leave solve_mode='auto'"
        )
    if cfg.gather_dtype not in ("f32", "bf16"):
        raise ValueError(
            f"gather_dtype must be 'f32' or 'bf16', got {cfg.gather_dtype!r}"
        )
    if cfg.fused_gather:
        raise ValueError(
            "fused_gather=True is not supported with shards > 1 (the "
            "fused Pallas build inside the sharded body is hardware-day "
            "headroom); leave the tri-state unset"
        )
    sort = cfg.sort_gather_indices
    return {
        "solve_mode": "chunked",
        "gather_dtype": cfg.gather_dtype,
        "sort_gather": True if sort is None else bool(sort),
        "fused_gather": False,
    }


def _permuted_table(table: np.ndarray, plan: ShardPlan) -> np.ndarray:
    """[n, R] global-order table → [S * cap, R] permuted layout (padding
    slots zero — required by the implicit psum'd Gramian and harmless
    everywhere else: no rating references them, no bucket solves them)."""
    n, rank = table.shape
    out = np.zeros((plan.shards * plan.cap, rank), dtype=np.float32)
    out[plan.flat_index(np.arange(n))] = np.asarray(table, dtype=np.float32)
    return out


def als_train_sharded(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    cfg: ALSConfig,
    shards: Optional[int] = None,
    mesh=None,
    devices=None,
    checkpoint=None,
    checkpoint_every: int = 0,
    profile: Optional[dict] = None,
) -> ALSFactors:
    """Train ALS with both factor tables sharded over ``shards`` devices.

    ``shards`` is the tri-state: explicit N wins, else :data:`SHARDS_ENV`,
    else 1 — and 1 IS the single-device trainer (the degenerate path
    delegates to :func:`~predictionio_tpu.ops.als.als_train` with the
    identical config, so ``shards=1`` and an unset tri-state on a single
    device resolve byte-identically). ``mesh`` (optional) supplies a
    prebuilt mesh whose :data:`SHARD_AXIS` size is the shard count —
    multi-host runs pass the ``hybrid_mesh`` built after
    ``initialize_from_env()`` (docs/hardware_day.md#multi-host-train);
    single-host runs build a mesh over the first ``shards`` devices.

    ``checkpoint`` (a :class:`~predictionio_tpu.ckpt.CheckpointStore`)
    enables sharded step-resume (docs/checkpoint.md): every
    ``checkpoint_every`` iterations both factor tables are snapshotted to
    host in CANONICAL (global, unpermuted) row order and committed by a
    background writer thread — the loop never stalls on disk. Because
    the snapshot is canonical, resume re-deals rows through the balancer
    at ANY shard count: a run checkpointed at N shards resumes at M and
    lands within the PR-12 reassociation tolerances of the uninterrupted
    run. Resuming against a mismatched recipe raises
    :class:`~predictionio_tpu.ckpt.CheckpointMismatch` (loud refusal); a
    corrupt step is skipped loudly to the previous valid one. When a
    store is passed, ``shards=1`` runs the sharded loop on a one-device
    mesh instead of delegating (the ckpt contract is tolerance-bounded,
    not byte-identical, and owns every shard count uniformly).

    ``profile`` receives the resolved levers (+ ``shards``), per-iteration
    wall clock, the ``shard_plan`` balance evidence (per-shard FLOPs,
    imbalance ratio, rows per shard) — the per-host bucket stats the
    hardware-day drive prints to confirm balance on real silicon — and,
    when checkpointing, a ``ckpt`` block (written/dropped/errors counts,
    snapshot seconds, the step resumed from).
    """
    import time as _time

    if cfg.iterations < 1:
        raise ValueError(f"ALS iterations must be >= 1, got {cfg.iterations}")
    if checkpoint_every < 0:
        raise ValueError(
            f"checkpoint_every must be >= 0, got {checkpoint_every}"
        )
    if checkpoint_every > 0 and checkpoint is None:
        raise ValueError(
            "checkpoint_every > 0 needs a checkpoint store — pass "
            "checkpoint=CheckpointStore(dir) (docs/checkpoint.md)"
        )
    n = resolve_shards(shards)
    if mesh is not None:
        n = int(mesh.shape[SHARD_AXIS])
    if n == 1 and checkpoint is None:
        # Degenerate path: byte-identical config resolution to today's
        # trainer — same bucketize call, same als_train, same profile
        # fields (plus the resolved shard count).
        by_user = bucketize(
            users, items, ratings, n_users, n_items, pad_to_blocks=True
        )
        by_item = bucketize(
            items, users, ratings, n_items, n_users, pad_to_blocks=True
        )
        factors = als_train(by_user, by_item, cfg, profile=profile)
        if profile is not None:
            profile["shards"] = 1
        return factors

    levers = resolve_sharded_levers(cfg)
    if mesh is None:
        pool = list(devices if devices is not None else jax.devices())
        if len(pool) < n:
            raise ValueError(
                f"shards={n} needs {n} devices, have {len(pool)} — on a "
                "single host force virtual devices with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "before importing jax (docs/distributed_training.md)"
            )
        mesh = create_mesh(MeshConfig(((SHARD_AXIS, n),)), pool[:n])

    users = np.ascontiguousarray(np.asarray(users), dtype=np.int32)
    items = np.ascontiguousarray(np.asarray(items), dtype=np.int32)
    ratings = np.ascontiguousarray(np.asarray(ratings), dtype=np.float32)
    rank = cfg.rank

    t_stage = _time.monotonic()
    user_deg = np.bincount(users, minlength=n_users)
    item_deg = np.bincount(items, minlength=n_items)
    user_plan = plan_side(user_deg, n, rank=rank)
    item_plan = plan_side(item_deg, n, rank=rank)
    sort = levers["sort_gather"]
    user_slabs_np, user_padded = _build_side(
        users, items, ratings, user_plan, item_plan,
        DEFAULT_BUCKET_WIDTHS, sort,
    )
    item_slabs_np, item_padded = _build_side(
        items, users, ratings, item_plan, user_plan,
        DEFAULT_BUCKET_WIDTHS, sort,
    )
    table_sharding = NamedSharding(mesh, P(SHARD_AXIS))
    slab_sharding = NamedSharding(mesh, P(SHARD_AXIS))
    put = lambda a: jax.device_put(a, slab_sharding)  # noqa: E731
    user_slabs = tuple(tuple(put(a) for a in slab) for slab in user_slabs_np)
    item_slabs = tuple(tuple(put(a) for a in slab) for slab in item_slabs_np)

    # Sharded step-resume (docs/checkpoint.md#resume-contract): the
    # config identity a checkpoint must match to be resumable. The shard
    # count is deliberately ABSENT — snapshots are canonical row order,
    # so any N resumes at any M; the balancer re-deals above.
    ck_meta = {
        "rank": cfg.rank,
        "lambda": cfg.lambda_,
        "alpha": cfg.alpha,
        "implicit": cfg.implicit_prefs,
        "seed": cfg.seed,
        "nnz": int(len(ratings)),
        "n_users": int(n_users),
        "n_items": int(n_items),
    }
    start_iter = 0
    y_canonical = None
    if checkpoint is not None:
        # mismatched recipe → CheckpointMismatch propagates (loud
        # refusal); corrupt steps are skipped + counted inside load()
        loaded = checkpoint.load(
            expect_meta=ck_meta, max_step=cfg.iterations
        )
        if loaded is not None:
            x_canonical = np.asarray(loaded.arrays["x"], np.float32)
            y_canonical = np.asarray(loaded.arrays["y"], np.float32)
            if x_canonical.shape != (n_users, rank) or (
                y_canonical.shape != (n_items, rank)
            ):
                from ..ckpt import CheckpointMismatch

                raise CheckpointMismatch(
                    f"step {loaded.step}: factor shapes "
                    f"{x_canonical.shape}/{y_canonical.shape} do not "
                    f"match this run's ({n_users}, {rank})/"
                    f"({n_items}, {rank})"
                )
            start_iter = int(loaded.meta.get("iteration", loaded.step))
            if profile is not None:
                profile["ckpt"] = {"resumedFrom": start_iter}
            if start_iter >= cfg.iterations:
                # the interrupted run had already finished its sweeps —
                # nothing to train, return the checkpointed factors
                if profile is not None:
                    profile["stage_s"] = _time.monotonic() - t_stage
                    profile["shards"] = n
                    profile["iteration_s"] = []  # zero sweeps re-run
                    profile.update(levers)
                return ALSFactors(
                    user_factors=jnp.asarray(x_canonical),
                    item_factors=jnp.asarray(y_canonical),
                    rank=rank,
                )

    # MLlib iteration order: item factors initialize, users solve first.
    # The SAME global init the single-device trainer mints, permuted —
    # every global row starts from the identical value at any shard count.
    # On resume the checkpointed canonical table replaces the init: the
    # loop consumes only y at an iteration boundary, so restoring y is
    # the complete sweep state (x is re-solved from it immediately).
    y = jax.device_put(
        _permuted_table(
            np.asarray(init_factors(n_items, rank, cfg.seed))
            if y_canonical is None else y_canonical,
            item_plan,
        ),
        table_sharding,
    )
    if profile is not None:
        profile["stage_s"] = _time.monotonic() - t_stage
        profile["shards"] = n
        profile.update(levers)
        flops = sum(
            rows * row_solve_flops(w, rank)
            for padded in (user_padded, item_padded)
            for w, rows in padded.items()
        )
        if cfg.implicit_prefs:
            flops += 2.0 * (n_users + n_items) * rank * rank  # YᵀY
        profile["flops_per_iteration"] = flops
        profile["shard_plan"] = {
            "shards": n,
            "rowsPerShard": {
                "user": user_plan.cap,
                "item": item_plan.cap,
            },
            "perShardFlops": {
                "user": [round(f, 1) for f in user_plan.per_shard_flops],
                "item": [round(f, 1) for f in item_plan.per_shard_flops],
            },
            "flopImbalance": {
                "user": round(user_plan.flop_imbalance, 4),
                "item": round(item_plan.flop_imbalance, 4),
            },
        }
        profile.setdefault("iteration_s", [])

    lam = jnp.float32(cfg.lambda_)
    alpha = jnp.float32(cfg.alpha)
    common = dict(
        mesh=mesh,
        rank=rank,
        implicit=cfg.implicit_prefs,
        gather_dtype=cfg.gather_dtype,
    )
    from ..obs.profile import default_telemetry

    _telemetry = default_telemetry()
    writer = None
    if checkpoint is not None and checkpoint_every > 0:
        from ..ckpt import CheckpointWriter, resolve_queue_depth

        writer = CheckpointWriter(
            checkpoint, queue_depth=resolve_queue_depth()
        )
    snapshot_s = 0.0
    x = None
    try:
        _ix_user = np.arange(n_users)
        _ix_item = np.arange(n_items)
        for it in range(start_iter, cfg.iterations):
            t_iter = _time.monotonic()
            x = _telemetry.call(
                "als_sharded_half", _half_sharded, y, user_slabs, lam,
                alpha, cap_x=user_plan.cap, **common,
            )
            y = _telemetry.call(
                "als_sharded_half", _half_sharded, x, item_slabs, lam,
                alpha, cap_x=item_plan.cap, **common,
            )
            if profile is not None:
                jax.block_until_ready((x, y))
                profile["iteration_s"].append(_time.monotonic() - t_iter)
            done = it + 1
            if writer is not None and (
                done % checkpoint_every == 0 or done == cfg.iterations
            ):
                # snapshot in CANONICAL row order — the layout any shard
                # count can re-permute — on the train thread (one host
                # gather per table); the disk write happens on the
                # writer thread behind the bounded queue
                t_snap = _time.monotonic()
                snap = {
                    "x": np.asarray(x)[user_plan.flat_index(_ix_user)],
                    "y": np.asarray(y)[item_plan.flat_index(_ix_item)],
                }
                meta = {**ck_meta, "iteration": done}
                if done == cfg.iterations:
                    # the final checkpoint is the run's durable result —
                    # it waits for a queue slot instead of dropping
                    writer.flush_submit(done, snap, meta)
                else:
                    writer.submit(done, snap, meta)
                snapshot_s += _time.monotonic() - t_snap
    finally:
        if writer is not None:
            stats = writer.close()
            if profile is not None:
                ck_prof = profile.setdefault("ckpt", {})
                ck_prof.update(stats)
                ck_prof["snapshotS"] = round(snapshot_s, 4)
                ck_prof["corruptSkipped"] = checkpoint.corrupt_skipped
                ck_prof.setdefault("resumedFrom", None)

    # permuted sharded layout → global row order (host-side unpermute)
    uf = np.asarray(x)[user_plan.flat_index(np.arange(n_users))]
    itf = np.asarray(y)[item_plan.flat_index(np.arange(n_items))]
    return ALSFactors(
        user_factors=jnp.asarray(uf),
        item_factors=jnp.asarray(itf),
        rank=rank,
    )
