"""Markov chain transition model on TPU.

Rebuild of ``e2/src/main/scala/io/prediction/e2/engine/MarkovChain.scala:25-89``.
The reference groups ``CoordinateMatrix`` entries by row, keeps the top-N
tallies per state row-normalized, and predicts with a sparse vector-matrix
product collected over an RDD.

TPU-first restatement: the ragged per-row top-N lists become fixed-shape
``[S, N]`` index/probability tables (padding rows with zero probability),
which is exactly the layout a TPU wants — ``predict`` is one jit'd
gather-scale-scatter, no host loop. Row normalization uses the FULL row sum
(before top-N truncation), matching the reference
(``MarkovChain.scala:38-43``: ``total`` is computed over all row entries).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class MarkovChainModel:
    """Top-N row-normalized transition tables (``MarkovChainModel``,
    ``MarkovChain.scala:57-89``).

    ``indices[s, j]`` / ``probs[s, j]``: the j-th retained transition out of
    state ``s``. Rows with fewer than N transitions are padded with
    ``probs == 0`` (index 0, harmless under scatter-add).
    """

    indices: np.ndarray  # [S, N] int32
    probs: np.ndarray  # [S, N] float32
    n: int

    @property
    def num_states(self) -> int:
        return self.indices.shape[0]

    def predict(self, current_state: Sequence[float]) -> np.ndarray:
        """Next-state distribution: Σ_s current[s] · P(s → ·)
        (``MarkovChainModel.predict``, ``MarkovChain.scala:67-88``)."""
        s = self.num_states
        cur = jnp.asarray(np.asarray(current_state, np.float32))

        @jax.jit
        def step(cur, idx, probs):
            contrib = probs * cur[:, None]  # [S, N]
            return jnp.zeros((s,), jnp.float32).at[idx.reshape(-1)].add(
                contrib.reshape(-1)
            )

        return np.asarray(step(cur, jnp.asarray(self.indices), jnp.asarray(self.probs)))


def train(
    entries: Sequence[Tuple[int, int, float]],
    top_n: int,
    num_states: int = 0,
) -> MarkovChainModel:
    """Build the model from (row, col, tally) entries
    (``MarkovChain.train``, ``MarkovChain.scala:32-54``).

    Per row: normalize by the row's full tally sum, keep the ``top_n``
    heaviest transitions. ``num_states`` defaults to max index + 1 (the
    reference takes it from ``matrix.numCols``).
    """
    if not entries:
        raise ValueError("Cannot train a Markov chain with no transitions")
    rows = np.array([e[0] for e in entries], np.int64)
    cols = np.array([e[1] for e in entries], np.int64)
    vals = np.array([e[2] for e in entries], np.float64)
    s = int(num_states or max(rows.max(), cols.max()) + 1)

    # Dense tally [S, S] via scatter-add, then per-row top-N — both one XLA
    # op each. (For state spaces too big for a dense S×S, the event-store
    # scan already buckets; dense is right for the reference's scale.)
    @jax.jit
    def build(r, c, v):
        tally = jnp.zeros((s, s), jnp.float32).at[r, c].add(v)
        totals = tally.sum(axis=1, keepdims=True)
        probs = jnp.where(totals > 0, tally / jnp.maximum(totals, 1e-30), 0.0)
        k = min(top_n, s)
        top_probs, top_idx = jax.lax.top_k(probs, k)
        return top_idx.astype(jnp.int32), top_probs

    idx, probs = build(
        jnp.asarray(rows, jnp.int32),
        jnp.asarray(cols, jnp.int32),
        jnp.asarray(vals, jnp.float32),
    )
    return MarkovChainModel(
        indices=np.asarray(idx), probs=np.asarray(probs), n=top_n
    )
