"""Random forest classifier on TPU.

The reference template's second algorithm wraps MLlib
``RandomForest.trainClassifier`` (``examples/scala-parallel-classification/
add-algorithm/src/main/scala/RandomForestAlgorithm.scala:28-41``) with params
``numClasses, numTrees, featureSubsetStrategy, impurity, maxDepth, maxBins``.

MLlib grows trees node-queue style with per-partition histogram aggregation.
The TPU-native formulation keeps the same statistical recipe — quantile-bin
histograms, gini/entropy split search, per-node feature subsets, bootstrap
bagging — but grows ALL nodes of a level for ALL trees in one fixed-shape
step:

- samples carry a ``node_id`` per tree; a level step is one scatter-add into
  a ``[T, nodes, D, B, C]`` histogram cube, one vectorized gain argmax, and
  one gather to route samples down — no host control flow, shapes static
  across the whole build, so XLA compiles a single fused program;
- trees live in a dense complete-binary-tree layout (``feature``,
  ``threshold`` per internal node, class histogram per node), so batched
  prediction is ``max_depth`` gathers.

Bins are global per-feature quantiles (MLlib also bins once up front).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ForestConfig:
    """``RandomForestAlgorithmParams`` analogue (defaults from the
    template's engine.json)."""

    num_classes: int = 2
    num_trees: int = 10
    feature_subset_strategy: str = "auto"  # auto | all | sqrt | log2 | onethird
    impurity: str = "gini"  # gini | entropy
    max_depth: int = 4
    max_bins: int = 32
    seed: int = 0


@dataclasses.dataclass
class RandomForestModel:
    """Dense complete-binary-tree ensemble.

    Internal nodes ``0 .. 2^depth-2``; node ``i``'s children are ``2i+1``,
    ``2i+2``. ``leaf_probs[t, leaf]`` are class distributions at depth
    ``max_depth``; prediction = argmax of the mean over trees (majority
    vote, as MLlib classification does).
    """

    feature: np.ndarray  # [T, I] int32 split feature per internal node
    threshold: np.ndarray  # [T, I] float32 split threshold
    leaf_probs: np.ndarray  # [T, L, C] float32
    class_values: np.ndarray  # [C] original label values
    max_depth: int

    def predict(self, features: Sequence[float]) -> float:
        return float(self.predict_batch(np.asarray(features)[None])[0])

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        """[N, D] → [N] label values: ``max_depth`` gathers per tree,
        vote across trees."""
        probs = _predict_probs(
            jnp.asarray(features, jnp.float32),
            jnp.asarray(self.feature),
            jnp.asarray(self.threshold),
            jnp.asarray(self.leaf_probs),
            self.max_depth,
        )
        return self.class_values[np.asarray(jnp.argmax(probs, axis=1))]

    def sanity_check(self) -> None:
        # +inf thresholds are the "unsplittable node" sentinel (route left);
        # only NaN indicates a broken build.
        if np.isnan(self.threshold).any():
            raise ValueError("RandomForestModel has NaN thresholds")


@functools.partial(jax.jit, static_argnames=("max_depth",))
def _predict_probs(x, feature, threshold, leaf_probs, max_depth):
    n = x.shape[0]
    t = feature.shape[0]
    node = jnp.zeros((t, n), jnp.int32)
    for _ in range(max_depth):
        f = jnp.take_along_axis(feature, node, axis=1)  # [T, N]
        thr = jnp.take_along_axis(threshold, node, axis=1)
        xv = x[jnp.arange(n)[None, :], f]  # [T, N]
        node = 2 * node + 1 + (xv > thr).astype(jnp.int32)
    leaf = node - (2**max_depth - 1)
    probs = jnp.take_along_axis(
        leaf_probs, leaf[:, :, None], axis=1
    )  # [T, N, C]
    return probs.mean(axis=0)  # [N, C]


def _impurity_from_hist(h: jnp.ndarray, kind: str) -> jnp.ndarray:
    """h[..., C] class counts → impurity[...] (gini or entropy)."""
    tot = h.sum(axis=-1, keepdims=True)
    p = h / jnp.maximum(tot, 1.0)
    if kind == "entropy":
        return -(jnp.where(p > 0, p * jnp.log(p), 0.0)).sum(axis=-1)
    return 1.0 - (p * p).sum(axis=-1)  # gini


def _features_per_node(strategy: str, d: int) -> int:
    s = strategy.lower()
    if s in ("all",):
        return d
    if s in ("sqrt", "auto"):  # MLlib auto = sqrt for classification
        return max(1, int(np.sqrt(d)))
    if s == "log2":
        return max(1, int(np.log2(d)))
    if s == "onethird":
        return max(1, d // 3)
    raise ValueError(f"Unknown featureSubsetStrategy: {strategy}")


def train(
    features: np.ndarray,  # [N, D]
    labels: np.ndarray,  # [N] label values
    config: ForestConfig = ForestConfig(),
    class_values: Optional[np.ndarray] = None,
) -> RandomForestModel:
    """Grow the ensemble level-by-level with fixed-shape device steps."""
    x_np = np.asarray(features, np.float32)
    labels = np.asarray(labels)
    n, d = x_np.shape
    if n == 0:
        raise ValueError("Cannot train a random forest on an empty dataset")

    if class_values is None:
        class_values, label_idx = np.unique(labels, return_inverse=True)
    else:
        class_values = np.asarray(class_values)
        label_idx = np.searchsorted(class_values, labels)
    c = max(config.num_classes, class_values.shape[0])

    # Global per-feature quantile bin edges [D, B-1] (MLlib findSplits).
    b = min(config.max_bins, max(2, n))
    qs = np.linspace(0, 1, b + 1)[1:-1]
    edges = np.quantile(x_np, qs, axis=0).T.astype(np.float32)  # [D, B-1]
    # binned[n, d] = number of edges < x  (so bin k means edges[k-1] < x <= edges[k])
    binned = (x_np[:, :, None] > edges[None]).sum(axis=2).astype(np.int32)

    t = config.num_trees
    depth = config.max_depth
    n_internal = 2**depth - 1
    n_leaves = 2**depth
    k_feats = _features_per_node(config.feature_subset_strategy, d)

    key = jax.random.PRNGKey(config.seed)
    boot_key, feat_key = jax.random.split(key)
    # bootstrap sample indices per tree [T, N]
    boot = jax.random.randint(boot_key, (t, n), 0, n, dtype=jnp.int32)

    xb = jnp.asarray(binned)  # [N, D] bin ids
    xe = jnp.asarray(edges)  # [D, B-1]
    yl = jnp.asarray(label_idx, jnp.int32)

    @functools.partial(jax.jit, static_argnames=("level",))
    def level_step(level, node, sample_idx, sample_y, feat_arr, thr_arr, fkey):
        """One level for all trees: histogram → best split → route down."""
        n_nodes = 2**level
        first = n_nodes - 1  # first node id at this level
        local = node - first  # [T, N] in [0, n_nodes)

        # class histograms per (tree, node, feature, bin)
        tree_ix = jnp.broadcast_to(jnp.arange(t)[:, None, None], (t, n, d))
        node_ix = jnp.broadcast_to(local[:, :, None], (t, n, d))
        feat_ix = jnp.broadcast_to(jnp.arange(d)[None, None, :], (t, n, d))
        bins = xb[sample_idx]  # [T, N, D]
        ys = jnp.broadcast_to(sample_y[:, :, None], (t, n, d))
        hist = jnp.zeros((t, n_nodes, d, b, c), jnp.float32).at[
            tree_ix.reshape(-1),
            node_ix.reshape(-1),
            feat_ix.reshape(-1),
            bins.reshape(-1),
            ys.reshape(-1),
        ].add(1.0)

        # split gain for each candidate boundary (after bin k, k=0..B-2)
        left = jnp.cumsum(hist, axis=3)[:, :, :, :-1, :]  # [T,Nn,D,B-1,C]
        total = hist.sum(axis=3)[:, :, :, None, :]  # [T,Nn,D,1,C]
        right = total - left
        lt = left.sum(axis=-1)
        rt = right.sum(axis=-1)
        nt = jnp.maximum(lt + rt, 1.0)
        child_imp = (
            lt * _impurity_from_hist(left, config.impurity)
            + rt * _impurity_from_hist(right, config.impurity)
        ) / nt  # [T,Nn,D,B-1]
        parent_imp = _impurity_from_hist(total[:, :, :, 0, :], config.impurity)
        gain = parent_imp[..., None] - child_imp  # [T,Nn,D,B-1]
        # invalid splits (empty side) get no gain
        gain = jnp.where((lt > 0) & (rt > 0), gain, -jnp.inf)

        # per-(tree,node) random feature subset (MLlib per-node subsetting)
        if k_feats < d:
            scores = jax.random.uniform(fkey, (t, n_nodes, d))
            kth = jnp.sort(scores, axis=2)[:, :, k_feats - 1][:, :, None]
            gain = jnp.where((scores <= kth)[..., None], gain, -jnp.inf)

        flat = gain.reshape(t, n_nodes, d * (b - 1))
        best = jnp.argmax(flat, axis=2)  # [T, Nn]
        best_gain = jnp.take_along_axis(flat, best[:, :, None], axis=2)[..., 0]
        bf = (best // (b - 1)).astype(jnp.int32)  # feature
        bb = best % (b - 1)  # boundary index
        bthr = xe[bf, bb]  # [T, Nn]
        # nodes with no valid split: route everything left via +inf threshold
        bthr = jnp.where(jnp.isfinite(best_gain), bthr, jnp.inf)

        feat_arr = feat_arr.at[:, first : first + n_nodes].set(bf)
        thr_arr = thr_arr.at[:, first : first + n_nodes].set(bthr)

        # route samples: compare raw value to threshold
        xv = jnp.take_along_axis(
            jnp.asarray(x_np)[sample_idx],  # [T, N, D]
            jnp.take_along_axis(bf, local, axis=1)[:, :, None],
            axis=2,
        )[..., 0]
        thr_s = jnp.take_along_axis(bthr, local, axis=1)
        node = 2 * node + 1 + (xv > thr_s).astype(jnp.int32)
        return node, feat_arr, thr_arr

    node = jnp.zeros((t, n), jnp.int32)
    sample_y = yl[boot]  # [T, N]
    feat_arr = jnp.zeros((t, n_internal), jnp.int32)
    thr_arr = jnp.full((t, n_internal), jnp.inf, jnp.float32)
    for level in range(depth):
        fkey = jax.random.fold_in(feat_key, level)
        node, feat_arr, thr_arr = level_step(
            level, node, boot, sample_y, feat_arr, thr_arr, fkey
        )

    # leaf class distributions
    leaf = node - (2**depth - 1)  # [T, N]
    tree_ix = jnp.broadcast_to(jnp.arange(t)[:, None], (t, n))

    @jax.jit
    def leaf_hist(leaf, sample_y):
        return jnp.zeros((t, n_leaves, c), jnp.float32).at[
            tree_ix.reshape(-1), leaf.reshape(-1), sample_y.reshape(-1)
        ].add(1.0)

    lh = leaf_hist(leaf, sample_y)
    probs = lh / jnp.maximum(lh.sum(axis=2, keepdims=True), 1.0)

    cv = np.zeros((c,), dtype=np.asarray(class_values).dtype)
    cv[: class_values.shape[0]] = class_values
    return RandomForestModel(
        feature=np.asarray(feat_arr),
        threshold=np.asarray(thr_arr),
        leaf_probs=np.asarray(probs),
        class_values=cv,
        max_depth=depth,
    )
