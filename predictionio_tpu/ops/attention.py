"""Attention kernels: blockwise (flash) and sequence-parallel (ring, Ulysses).

The reference has no sequence models at all (SURVEY §5 "Long-context /
sequence parallelism — absent"; its nearest neighbor is the MarkovChain
transition matrix, ``e2/.../MarkovChain.scala``). This framework treats
long-context as first-class: the sequence-recommendation engine
(:mod:`predictionio_tpu.models.sequencerec`) and any future sequence model
train over context windows sharded across the mesh ``seq`` axis.

Three schedules, one math:

- :func:`flash_attention` — single-device blockwise attention with an online
  softmax (``lax.scan`` over KV blocks): O(block²) memory instead of O(L²),
  XLA fuses the inner matmuls onto the MXU.
- :func:`ring_attention` — sequence parallelism over a mesh axis: every
  device keeps its Q chunk, KV chunks rotate around the ring via
  ``ppermute`` (ICI neighbor exchanges), partial results merge with the same
  online-softmax rescaling. Peak memory per device is O(L²/N²) score tiles;
  communication overlaps compute chunk by chunk.
- :func:`ulysses_attention` — all-to-all alternative: resharding seq→heads
  before attention and heads→seq after, so each device runs *full-sequence*
  attention for a subset of heads. Two all-to-alls instead of N-1 ring
  hops — better when heads ≥ devices and ICI all-to-all bandwidth is good.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import shard_map  # jax-version compat shim

try:  # pallas is TPU/GPU-oriented; keep the module importable anywhere
    from jax.experimental import pallas as pl

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

SEQ_AXIS = "seq"

_NEG_BIG = -1e30  # additive mask value (finite: keeps fully-masked rows NaN-free)


def _attend_block(q, k, v, m, l, o, mask, scale):
    """One online-softmax accumulation step.

    q [..., Lq, D], k/v [..., Lk, D]; running (m, l, o) with m/l [..., Lq]
    and o [..., Lq, D]; ``mask`` is an optional [Lq, Lk] bool (True = keep).
    """
    scores = jnp.einsum(
        "...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_BIG)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_q, blk_k, lk,
                  causal, scale, n_kv):
    """One (batch·head, Q-block) grid step: online softmax over KV blocks.

    Everything lives in VMEM: q block [blk_q, D], full K/V [Lk_pad, D]
    (fetched once per batch·head — the Q-block grid dim is innermost and
    their index map is constant in it), score tiles [blk_q, blk_k] that
    never touch HBM — the O(L²) score matrix is the thing this kernel
    exists to not materialize.
    """
    i = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [blk_q, D]
    d = q.shape[-1]
    q_pos = i * blk_q + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0
    )

    def step(j, carry):
        m, l, o = carry
        kj = k_ref[0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        vj = v_ref[0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [blk_q, blk_k]
        k_pos = j * blk_k + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 1
        )
        keep = k_pos < lk
        if causal:
            keep = keep & (q_pos >= k_pos)
        s = jnp.where(keep, s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + p.sum(axis=1)
        o_new = o * corr[:, None] + jax.lax.dot_general(
            p, vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, o_new

    m0 = jnp.full((blk_q,), _NEG_BIG, dtype=jnp.float32)
    l0 = jnp.zeros((blk_q,), dtype=jnp.float32)
    o0 = jnp.zeros((blk_q, d), dtype=jnp.float32)
    # causal: KV blocks strictly above this Q block's diagonal contribute
    # nothing — skip them (the classic flash-attention work saving)
    hi = (
        jnp.minimum(((i + 1) * blk_q + blk_k - 1) // blk_k, n_kv)
        if causal else n_kv
    )
    m, l, o = jax.lax.fori_loop(0, hi, step, (m0, l0, o0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "blk_q", "blk_k", "interpret")
)
def _flash_pallas_call(q, k, v, causal, blk_q, blk_k, interpret):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    lq_pad = -lq % blk_q
    lk_pad = -lk % blk_k
    if lq_pad:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, lq_pad), (0, 0)))
    if lk_pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, lk_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, lk_pad), (0, 0)))
    bh = b * h
    qr = q.reshape(bh, lq + lq_pad, d)
    kr = k.reshape(bh, lk + lk_pad, d)
    vr = v.reshape(bh, lk + lk_pad, d)
    n_kv = (lk + lk_pad) // blk_k
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, blk_q=blk_q, blk_k=blk_k, lk=lk,
            causal=causal, scale=1.0 / np.sqrt(d), n_kv=n_kv,
        ),
        grid=(bh, (lq + lq_pad) // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda bhi, i: (bhi, i, 0)),
            pl.BlockSpec((1, lk + lk_pad, d), lambda bhi, i: (bhi, 0, 0)),
            pl.BlockSpec((1, lk + lk_pad, d), lambda bhi, i: (bhi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, d), lambda bhi, i: (bhi, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq + lq_pad, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, lq + lq_pad, d)[:, :, :lq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_pallas_diff(q, k, v, causal, blk_q, blk_k, interpret):
    return _flash_pallas_call(q, k, v, causal, blk_q, blk_k, interpret)


def _flash_pallas_fwd(q, k, v, causal, blk_q, blk_k, interpret):
    # flash-style backward: save only q/k/v and recompute attention in
    # the VJP (the O(L²) score matrix is never a residual) — here the
    # recompute runs through the XLA online-softmax path, whose autodiff
    # is the reference math the kernel is equality-tested against
    return (
        _flash_pallas_call(q, k, v, causal, blk_q, blk_k, interpret),
        (q, k, v),
    )


def _flash_pallas_bwd(causal, blk_q, blk_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=causal),
        q, k, v,
    )
    return vjp(g)


_flash_pallas_diff.defvjp(_flash_pallas_fwd, _flash_pallas_bwd)


def flash_attention_pallas(
    q: jax.Array,  # [B, H, L, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Pallas flash attention: fused scores+softmax+PV per Q block, causal
    upper-triangle KV blocks skipped entirely. K/V are VMEM-resident per
    batch·head, so this single-device kernel targets L up to the VMEM
    budget (~16k at D=64); beyond that, shard the sequence (ring/Ulysses
    — which is the framework's long-context answer anyway).

    Differentiable: a custom VJP recomputes attention through the XLA
    online-softmax path in the backward pass (flash-style — only q/k/v
    are residuals, never the score matrix), so training through this
    kernel is supported.

    EXPERIMENTAL: selected via ``attention(..., impl="pallas")`` /
    ``flash_impl`` in sequencerec params, XLA path remains the default
    until the Mosaic lowering is hardware-validated (``flash_pallas``
    step in the revalidation queue). ``interpret=None`` auto-selects the
    interpreter off-TPU.
    """
    if not _HAVE_PALLAS:
        raise NotImplementedError(
            "flash_attention_pallas requires pallas; use flash_attention"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lq, lk = q.shape[2], k.shape[2]
    return _flash_pallas_diff(
        q, k, v, causal, min(block_q, max(8, lq)), min(block_k, lk),
        interpret,
    )


@functools.partial(jax.jit, static_argnames=("causal", "block_k"))
def flash_attention(
    q: jax.Array,  # [B, H, L, D]
    k: jax.Array,  # [B, H, L, D]
    v: jax.Array,  # [B, H, L, D]
    causal: bool = True,
    block_k: int = 512,
) -> jax.Array:
    """Blockwise attention with online softmax (single device)."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = 1.0 / np.sqrt(d)
    blk = min(block_k, lk)
    n_blocks = (lk + blk - 1) // blk
    pad = n_blocks * blk - lk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))

    q_pos = jnp.arange(lq)
    kb = k.reshape(b, h, n_blocks, blk, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, n_blocks, blk, d).transpose(2, 0, 1, 3, 4)

    qf = q.astype(jnp.float32)

    def step(carry, inputs):
        m, l, o = carry
        (j, kj, vj) = inputs
        k_pos = j * blk + jnp.arange(blk)
        valid = k_pos < lk  # padded keys masked out
        if causal:
            mask = (q_pos[:, None] >= k_pos[None, :]) & valid[None, :]
        else:
            mask = jnp.broadcast_to(valid[None, :], (lq, blk))
        m, l, o = _attend_block(
            qf, kj.astype(jnp.float32), vj, m, l, o, mask, scale
        )
        return (m, l, o), None

    m0 = jnp.full((b, h, lq), _NEG_BIG, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, lq), dtype=jnp.float32)
    o0 = jnp.zeros((b, h, lq, d), dtype=jnp.float32)
    (m, l, o), _ = jax.lax.scan(
        step, (m0, l0, o0), (jnp.arange(n_blocks), kb, vb)
    )
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, H, L, D] — L sharded over `axis`
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = SEQ_AXIS,
    causal: bool = True,
) -> jax.Array:
    """Sequence-parallel attention: KV chunks rotate around the mesh ring.

    Inputs/outputs are length-sharded over ``axis`` (chunk i on device i,
    contiguous order). Each of the N ring steps attends the local Q chunk to
    the visiting KV chunk with global-position causal masking, merging via
    online-softmax rescaling; ``ppermute`` moves KV to the next neighbor —
    N-1 ICI hops, never materializing more than one remote chunk.
    """
    n = mesh.shape[axis]
    b, h, l, d = q.shape
    assert l % n == 0, f"sequence length {l} not divisible by ring size {n}"
    chunk = l // n
    scale = 1.0 / np.sqrt(d)

    def local(qc, kc, vc):
        # qc/kc/vc: [B, H, chunk, D] local shards
        my = jax.lax.axis_index(axis)
        q_pos = my * chunk + jnp.arange(chunk)
        qf = qc.astype(jnp.float32)

        def step(s, carry):
            m, l_, o, kc_, vc_ = carry
            src = (my - s) % n  # owner of the currently-visiting KV chunk
            k_pos = src * chunk + jnp.arange(chunk)
            mask = (q_pos[:, None] >= k_pos[None, :]) if causal else None
            m, l_, o = _attend_block(
                qf, kc_.astype(jnp.float32), vc_, m, l_, o, mask, scale,
            )
            perm = [(i, (i + 1) % n) for i in range(n)]
            kc_ = jax.lax.ppermute(kc_, axis, perm)
            vc_ = jax.lax.ppermute(vc_, axis, perm)
            return m, l_, o, kc_, vc_

        m0 = jnp.full((b, h, chunk), _NEG_BIG, dtype=jnp.float32)
        l0 = jnp.zeros((b, h, chunk), dtype=jnp.float32)
        o0 = jnp.zeros((b, h, chunk, d), dtype=jnp.float32)
        m, l_, o, _, _ = jax.lax.fori_loop(
            0, n, step, (m0, l0, o0, kc, vc)
        )
        return (o / jnp.maximum(l_, 1e-30)[..., None]).astype(qc.dtype)

    spec = P(None, None, axis, None)
    f = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(f)(q, k, v)


def ulysses_attention(
    q: jax.Array,  # [B, H, L, D] — L sharded over `axis`
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = SEQ_AXIS,
    causal: bool = True,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses schedule):
    reshard seq→heads, full-sequence attention per head subset, reshard
    heads→seq. Requires ``H % mesh.shape[axis] == 0``."""
    n = mesh.shape[axis]
    b, h, l, d = q.shape
    assert h % n == 0, f"{h} heads not divisible by {n} devices"
    assert l % n == 0, f"sequence length {l} not divisible by {n} devices"

    def local(qc, kc, vc):
        # [B, H, L/N, D] → all-to-all → [B, H/N, L, D]
        def a2a_in(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=1, concat_axis=2, tiled=True
            )

        def a2a_out(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        qh, kh, vh = a2a_in(qc), a2a_in(kc), a2a_in(vc)
        oh = flash_attention(qh, kh, vh, causal=causal)
        return a2a_out(oh)

    spec = P(None, None, axis, None)
    f = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(f)(q, k, v)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis: str = SEQ_AXIS,
    causal: bool = True,
    schedule: str = "auto",
    impl: str = "xla",
) -> jax.Array:
    """Dispatch: single-device flash when no mesh / 1-device axis; otherwise
    ring (default) or Ulysses (``schedule="ulysses"``, when heads divide).
    ``impl="pallas"`` selects the fused single-device kernel
    (:func:`flash_attention_pallas`; experimental, hardware-gated) —
    sharded schedules keep the XLA inner step for now."""
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown attention impl {impl!r}")
    if mesh is None or axis not in mesh.shape or mesh.shape[axis] == 1:
        if impl == "pallas":
            return flash_attention_pallas(q, k, v, causal=causal)
        return flash_attention(q, k, v, causal=causal)
    if schedule == "ulysses":
        return ulysses_attention(q, k, v, mesh, axis, causal)
    if schedule not in ("auto", "ring"):
        raise ValueError(f"unknown attention schedule {schedule!r}")
    return ring_attention(q, k, v, mesh, axis, causal)
