"""TPU compute kernels: ALS, scoring, classification reductions (SURVEY §2.9:
the MLlib-dependency surface rebuilt as XLA/Pallas programs)."""

from .als import (
    ALSConfig,
    ALSFactors,
    BucketedMatrix,
    als_train,
    als_train_coo,
    bucketize,
    predict_pairs,
    rmse,
)
from .scoring import (
    standardize,
    top_k_for_users,
    top_k_for_vectors,
    top_k_similar_items,
)

__all__ = [
    "ALSConfig",
    "ALSFactors",
    "BucketedMatrix",
    "als_train",
    "als_train_coo",
    "bucketize",
    "predict_pairs",
    "rmse",
    "standardize",
    "top_k_for_users",
    "top_k_for_vectors",
    "top_k_similar_items",
]
