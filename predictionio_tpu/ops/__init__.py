"""TPU compute kernels: ALS, scoring, classification reductions (SURVEY §2.9:
the MLlib-dependency surface rebuilt as XLA/Pallas programs)."""

from .als import (
    ALSConfig,
    ALSFactors,
    BucketedMatrix,
    als_train,
    als_train_coo,
    bucketize,
    predict_pairs,
    rmse,
)
from .als_sharded import als_train_sharded, resolve_shards
from . import classifier, forest, markov, naive_bayes
from .scoring import (
    standardize,
    top_k_for_users,
    top_k_for_users_fused,
    top_k_for_vectors,
    top_k_fused_vectors,
    top_k_similar_items,
    top_k_similar_items_fused,
)

__all__ = [
    "ALSConfig",
    "classifier",
    "forest",
    "markov",
    "naive_bayes",
    "ALSFactors",
    "BucketedMatrix",
    "als_train",
    "als_train_coo",
    "als_train_sharded",
    "resolve_shards",
    "bucketize",
    "predict_pairs",
    "rmse",
    "standardize",
    "top_k_for_users",
    "top_k_for_users_fused",
    "top_k_for_vectors",
    "top_k_fused_vectors",
    "top_k_similar_items",
    "top_k_similar_items_fused",
]
