// Shared internals of the native event log (record layout + handle), used
// by eventlog.cc (storage engine) and ratings.cc (training-infeed scan).
// See eventlog.cc for the format documentation.

#ifndef PIO_EVENTLOG_INTERNAL_H_
#define PIO_EVENTLOG_INTERNAL_H_

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

#include <sys/file.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace pio {

constexpr uint32_t kHeaderSize = 80;
constexpr uint32_t kFlagTombstone = 1u;

#pragma pack(push, 1)
struct RecordHeader {
  uint32_t record_len;
  uint32_t flags;
  int64_t event_time_ms;
  int64_t creation_time_ms;
  uint64_t etype_hash;
  uint64_t entity_hash;
  uint64_t event_hash;
  uint64_t ttype_hash;
  uint64_t target_hash;
  uint64_t id_hash;
  uint32_t payload_len;
  uint32_t reserved;
};
#pragma pack(pop)

static_assert(sizeof(RecordHeader) == kHeaderSize, "header must be 80 bytes");

struct Handle {
  int fd = -1;
  int64_t size = 0;       // committed (validated) file size
  int64_t n_records = 0;  // records incl. tombstones
  std::mutex mu;
  std::string path;
};

// RAII advisory whole-file lock (cross-process append serialization).
struct FileLock {
  int fd;
  bool held;
  explicit FileLock(int fd_) : fd(fd_), held(flock(fd_, LOCK_EX) == 0) {}
  ~FileLock() {
    if (held) flock(fd, LOCK_UN);
  }
};

// Validate records in [from, file_size); set *committed to the offset of the
// first invalid byte and *count to the number of valid records seen. Returns
// false when the file could not be inspected at all (mmap failure) — callers
// must NOT truncate in that case.
inline bool validate_range(int fd, int64_t file_size, int64_t from,
                           int64_t* committed, int64_t* count) {
  *committed = from;
  *count = 0;
  if (file_size - from < (int64_t)kHeaderSize) return true;
  void* map = mmap(nullptr, (size_t)file_size, PROT_READ, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) return false;
  const uint8_t* base = (const uint8_t*)map;
  int64_t off = from;
  while (off + (int64_t)kHeaderSize <= file_size) {
    RecordHeader h;
    memcpy(&h, base + off, kHeaderSize);
    if (h.record_len < kHeaderSize || h.record_len % 8 != 0 ||
        off + (int64_t)h.record_len > file_size ||
        h.payload_len > h.record_len - kHeaderSize) {
      break;
    }
    off += h.record_len;
    (*count)++;
  }
  munmap(map, (size_t)file_size);
  *committed = off;
  return true;
}

// Pick up records appended through other handles/processes (O_APPEND writers
// on the same file): extend h->size over any newly committed tail. Caller
// must hold h->mu. On inspection failure the old bound is kept (safe: scans
// just miss the newest records until the next successful refresh).
inline void refresh_size(Handle* h) {
  struct stat st;
  if (fstat(h->fd, &st) != 0) return;
  if ((int64_t)st.st_size <= h->size) return;
  int64_t committed, count;
  if (validate_range(h->fd, (int64_t)st.st_size, h->size, &committed, &count)) {
    h->size = committed;
    h->n_records += count;
  }
}

}  // namespace pio

#endif  // PIO_EVENTLOG_INTERNAL_H_
