// Native training-infeed scan: event log -> dense rating triples.
//
// The reference's training read path hands Spark executors raw HBase rows
// that user DataSource code re-parses per event on the JVM
// (HBPEvents.scala:91-97 + the template's DataSource.scala:25-55). At 20M
// events the equivalent per-event Python decode costs minutes; this scan
// does the whole DataSource inner loop natively in one pass over the mmap'd
// log:
//
//   header prefilter (event-name hashes, tombstones)  ->
//   minimal JSON field extraction (entityId, targetEntityId,
//   properties.<prop>)  ->
//   first-occurrence id interning into dense int32 indices
//
// and returns int32/float32 arrays plus the two unique-id string pools.
// Python materializes only the unique ids (~1e5 objects), never the 20M
// per-event strings. Ordering matches evlog_scan: (event_time_ms, offset)
// ascending, so index assignment is identical to the Python streaming path
// run over the same scan.
//
// Value rules mirror the recommendation template's rate/buy pattern-match:
// per event-name either "read numeric property <prop_name>" or a fixed
// value. A record whose payload's "event" string does not byte-match the
// expected name for its header hash is skipped (the same 64-bit
// hash-collision re-verification the Python scan layer performs).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

#include <sys/mman.h>

#include "eventlog_internal.h"

using pio::Handle;
using pio::kFlagTombstone;
using pio::kHeaderSize;
using pio::RecordHeader;
using pio::refresh_size;

namespace {

// ---------------------------------------------------------------------------
// Minimal JSON walker: enough to pull two string fields and one numeric
// property out of a trusted wire-format event dict (the log only ever stores
// payloads our own writer serialized; malformed payloads are skipped).
// ---------------------------------------------------------------------------
struct JsonCursor {
  const char* p;
  const char* end;
  bool ok = true;

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool at(char c) {
    skip_ws();
    return p < end && *p == c;
  }
  bool eat(char c) {
    skip_ws();
    if (p < end && *p == c) {
      p++;
      return true;
    }
    ok = false;
    return false;
  }
  // Parse a JSON string starting at '"'; append decoded bytes to out.
  bool parse_string(std::string* out) {
    if (!eat('"')) return false;
    // fast path: span to the closing quote contains no escapes
    {
      const char* q =
          (const char*)memchr(p, '"', (size_t)(end - p));
      if (q == nullptr) { ok = false; return false; }
      if (memchr(p, '\\', (size_t)(q - p)) == nullptr) {
        if (out) out->append(p, (size_t)(q - p));
        p = q + 1;
        return true;
      }
    }
    while (p < end) {
      char c = *p++;
      if (c == '"') return true;
      if (c != '\\') {
        if (out) out->push_back(c);
        continue;
      }
      if (p >= end) break;
      char e = *p++;
      switch (e) {
        case '"': if (out) out->push_back('"'); break;
        case '\\': if (out) out->push_back('\\'); break;
        case '/': if (out) out->push_back('/'); break;
        case 'b': if (out) out->push_back('\b'); break;
        case 'f': if (out) out->push_back('\f'); break;
        case 'n': if (out) out->push_back('\n'); break;
        case 'r': if (out) out->push_back('\r'); break;
        case 't': if (out) out->push_back('\t'); break;
        case 'u': {
          if (end - p < 4) { ok = false; return false; }
          unsigned cp = 0;
          for (int i = 0; i < 4; i++) {
            char h = *p++;
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= (unsigned)(h - '0');
            else if (h >= 'a' && h <= 'f') cp |= (unsigned)(h - 'a' + 10);
            else if (h >= 'A' && h <= 'F') cp |= (unsigned)(h - 'A' + 10);
            else { ok = false; return false; }
          }
          // UTF-8 encode (surrogate pairs: encode each half as-is is wrong,
          // but our writer never emits raw surrogates — json.dumps uses
          // ensure_ascii=False or pairs; handle pairs correctly anyway).
          if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[0] == '\\' &&
              p[1] == 'u') {
            unsigned lo = 0;
            const char* q = p + 2;
            bool good = true;
            for (int i = 0; i < 4; i++) {
              char h = q[i];
              lo <<= 4;
              if (h >= '0' && h <= '9') lo |= (unsigned)(h - '0');
              else if (h >= 'a' && h <= 'f') lo |= (unsigned)(h - 'a' + 10);
              else if (h >= 'A' && h <= 'F') lo |= (unsigned)(h - 'A' + 10);
              else { good = false; break; }
            }
            if (good && lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              p += 6;
            }
          }
          if (out) {
            if (cp < 0x80) out->push_back((char)cp);
            else if (cp < 0x800) {
              out->push_back((char)(0xC0 | (cp >> 6)));
              out->push_back((char)(0x80 | (cp & 0x3F)));
            } else if (cp < 0x10000) {
              out->push_back((char)(0xE0 | (cp >> 12)));
              out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
              out->push_back((char)(0x80 | (cp & 0x3F)));
            } else {
              out->push_back((char)(0xF0 | (cp >> 18)));
              out->push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
              out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
              out->push_back((char)(0x80 | (cp & 0x3F)));
            }
          }
          break;
        }
        default:
          ok = false;
          return false;
      }
    }
    ok = false;
    return false;
  }
  // Skip any JSON value.
  bool skip_value() {
    skip_ws();
    if (p >= end) { ok = false; return false; }
    char c = *p;
    if (c == '"') return parse_string(nullptr);
    if (c == '{' || c == '[') {
      char open = c, close = (c == '{') ? '}' : ']';
      int depth = 0;
      bool in_str = false;
      while (p < end) {
        char d = *p++;
        if (in_str) {
          if (d == '\\') { if (p < end) p++; }
          else if (d == '"') in_str = false;
        } else {
          if (d == '"') in_str = true;
          else if (d == open) depth++;
          else if (d == close) {
            if (--depth == 0) return true;
          }
        }
      }
      ok = false;
      return false;
    }
    // number / true / false / null
    while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
           *p != '\t' && *p != '\n' && *p != '\r')
      p++;
    return true;
  }
  bool parse_number(double* out) {
    // Locale-independent: strtod honors LC_NUMERIC (a host process that
    // setlocale()d to a comma-decimal locale would silently truncate
    // "4.5" at the dot), so parse the JSON number grammar by hand.
    skip_ws();
    const char* q = p;
    bool neg = false;
    if (q < end && (*q == '-' || *q == '+')) { neg = (*q == '-'); q++; }
    double v = 0.0;
    const char* digits_start = q;
    while (q < end && *q >= '0' && *q <= '9') v = v * 10.0 + (*q++ - '0');
    if (q == digits_start) { ok = false; return false; }
    if (q < end && *q == '.') {
      q++;
      double scale = 0.1;
      while (q < end && *q >= '0' && *q <= '9') {
        v += (*q++ - '0') * scale;
        scale *= 0.1;
      }
    }
    if (q < end && (*q == 'e' || *q == 'E')) {
      q++;
      bool eneg = false;
      if (q < end && (*q == '-' || *q == '+')) { eneg = (*q == '-'); q++; }
      int ex = 0;
      const char* exp_start = q;
      while (q < end && *q >= '0' && *q <= '9') ex = ex * 10 + (*q++ - '0');
      if (q == exp_start) { ok = false; return false; }
      double f = 1.0;
      for (int i = 0; i < ex && i < 350; i++) f *= 10.0;
      v = eneg ? v / f : v * f;
    }
    *out = neg ? -v : v;
    p = q;
    return true;
  }
};

struct ParsedEvent {
  // Reused across records: clear() keeps string capacity, so steady-state
  // parsing allocates nothing for repeat-length ids.
  std::string event;
  std::string entity_id;
  std::string target_id;
  bool has_target = false;
  double prop_val = 0.0;
  bool has_prop = false;

  void reset() {
    event.clear();
    entity_id.clear();
    target_id.clear();
    has_target = false;
    prop_val = 0.0;
    has_prop = false;
  }
};

// Allocation-free key scan: copy the next JSON string into buf (cap bytes)
// IF it contains no escapes and fits; otherwise fall back to full parse
// into spill. Returns length, or -1 on error; *spilled set when fallback.
int key_scan(JsonCursor* c, char* buf, int cap, std::string* spill,
             bool* spilled) {
  *spilled = false;
  c->skip_ws();
  if (c->p >= c->end || *c->p != '"') { c->ok = false; return -1; }
  const char* q = c->p + 1;
  int n = 0;
  while (q < c->end && n < cap) {
    char ch = *q;
    if (ch == '"') {
      memcpy(buf, c->p + 1, (size_t)n);
      c->p = q + 1;
      return n;
    }
    if (ch == '\\') break;  // escaped key: rare — full parse
    q++;
    n++;
  }
  spill->clear();
  if (!c->parse_string(spill)) return -1;
  *spilled = true;
  return (int)spill->size();
}

// Walk the top-level object, extracting event/entityId/targetEntityId and
// properties.<prop_name>. Returns false on malformed payload.
bool parse_event_payload(const char* data, int64_t len, const char* prop_name,
                         size_t prop_len, ParsedEvent* out,
                         std::string* scratch) {
  JsonCursor c{data, data + len};
  if (!c.eat('{')) return false;
  if (c.at('}')) return true;
  char kbuf[40];
  while (c.ok) {
    bool spilled;
    int klen = key_scan(&c, kbuf, (int)sizeof(kbuf), scratch, &spilled);
    if (klen < 0) return false;
    const char* key = spilled ? scratch->data() : kbuf;
    if (!c.eat(':')) return false;
    if (klen == 5 && memcmp(key, "event", 5) == 0) {
      if (!c.parse_string(&out->event)) return false;
    } else if (klen == 8 && memcmp(key, "entityId", 8) == 0) {
      if (!c.parse_string(&out->entity_id)) return false;
    } else if (klen == 14 && memcmp(key, "targetEntityId", 14) == 0) {
      if (c.at('n')) {  // null
        if (!c.skip_value()) return false;
      } else {
        if (!c.parse_string(&out->target_id)) return false;
        out->has_target = true;
      }
    } else if (klen == 10 && memcmp(key, "properties", 10) == 0 &&
               prop_len > 0) {
      // descend one level looking for prop_name
      if (!c.eat('{')) return false;
      if (!c.at('}')) {
        while (c.ok) {
          int plen = key_scan(&c, kbuf, (int)sizeof(kbuf), scratch, &spilled);
          if (plen < 0) return false;
          const char* pkey = spilled ? scratch->data() : kbuf;
          if (!c.eat(':')) return false;
          if ((size_t)plen == prop_len &&
              memcmp(pkey, prop_name, prop_len) == 0) {
            if (!c.parse_number(&out->prop_val)) return false;
            out->has_prop = true;
          } else {
            if (!c.skip_value()) return false;
          }
          if (c.at(',')) { c.eat(','); continue; }
          break;
        }
      }
      if (!c.eat('}')) return false;
    } else {
      if (!c.skip_value()) return false;
    }
    if (c.at(',')) { c.eat(','); continue; }
    break;
  }
  return c.eat('}');
}

// First-occurrence string interner (dense index assignment). Lookups take
// the caller's reusable buffer by reference — repeat ids (the overwhelming
// majority at 145 ratings/user) allocate nothing.
struct Interner {
  std::unordered_map<std::string, int32_t> map;
  std::deque<std::string> order;  // index -> id string

  int32_t index(const std::string& s) {
    auto it = map.find(s);
    if (it != map.end()) return it->second;
    int32_t idx = (int32_t)order.size();
    order.push_back(s);
    map.emplace(order.back(), idx);
    return idx;
  }
};

struct HeaderMatch {
  int64_t time_ms;
  int64_t off;
  int64_t len;
  int32_t rule;  // index into the value-rule arrays
};

struct RatingsResult {
  std::vector<int32_t> users;
  std::vector<int32_t> items;
  std::vector<float> vals;
  Interner user_ix;
  Interner item_ix;
  int32_t error = 0;  // counts of skipped malformed payloads
};

}  // namespace

extern "C" {

// Scan the log for live records whose event hash is one of event_hashes.
// Per event i: value_is_prop[i] != 0 -> read properties.<prop_name>
// (required; missing -> record skipped + counted in *out_bad), else the
// fixed value fixed_vals[i]. event_names is the concatenation of the
// expected event-name strings (NUL-separated, n entries) for exact
// re-verification against the payload. Records without a target entity are
// skipped. Returns an opaque result handle (free with evlog_ratings_free),
// or nullptr on mmap failure. The number of ratings is written to *out_n.
void* evlog_ratings_scan(void* vh, const uint64_t* event_hashes,
                         const int32_t* value_is_prop,
                         const double* fixed_vals, int32_t n_events,
                         const char* event_names, const char* prop_name,
                         int64_t* out_n, int64_t* out_bad) {
  auto* h = (Handle*)vh;
  *out_n = 0;
  *out_bad = 0;
  int64_t size;
  {
    std::lock_guard<std::mutex> lock(h->mu);
    refresh_size(h);
    size = h->size;
  }
  auto* res = new RatingsResult();
  if (size < (int64_t)kHeaderSize) return res;
  void* map = mmap(nullptr, (size_t)size, PROT_READ, MAP_SHARED, h->fd, 0);
  if (map == MAP_FAILED) {
    delete res;
    return nullptr;
  }
  madvise(map, (size_t)size, MADV_SEQUENTIAL);
  const uint8_t* base = (const uint8_t*)map;

  // split the NUL-separated expected names
  std::vector<std::string> names;
  {
    const char* q = event_names;
    for (int32_t i = 0; i < n_events; i++) {
      names.emplace_back(q);
      q += names.back().size() + 1;
    }
  }
  std::unordered_map<uint64_t, int32_t> rule_of;
  for (int32_t i = 0; i < n_events; i++) rule_of.emplace(event_hashes[i], i);

  // pass 1: header walk — live matches with order-sensitive tombstones.
  // Fast path first: training logs almost never contain deletes, so walk
  // without per-id liveness tracking; on the first tombstone, restart with
  // the exact (order-sensitive) tracking walk.
  std::vector<HeaderMatch> matches;
  bool has_tombstone = false;
  {
    int64_t off = 0;
    while (off + (int64_t)kHeaderSize <= size) {
      RecordHeader hd;
      memcpy(&hd, base + off, kHeaderSize);
      if (hd.record_len < kHeaderSize || off + (int64_t)hd.record_len > size)
        break;
      if (hd.flags & kFlagTombstone) {
        has_tombstone = true;
        break;
      }
      if (hd.ttype_hash != 0) {  // target required
        auto it = rule_of.find(hd.event_hash);
        if (it != rule_of.end()) {
          matches.push_back({hd.event_time_ms, off + (int64_t)kHeaderSize,
                             (int64_t)hd.payload_len, it->second});
        }
      }
      off += hd.record_len;
    }
  }
  if (has_tombstone) {
    matches.clear();
    std::vector<bool> dead;
    std::unordered_map<uint64_t, std::vector<size_t>> live_by_id;
    int64_t off = 0;
    while (off + (int64_t)kHeaderSize <= size) {
      RecordHeader hd;
      memcpy(&hd, base + off, kHeaderSize);
      if (hd.record_len < kHeaderSize || off + (int64_t)hd.record_len > size)
        break;
      if (hd.flags & kFlagTombstone) {
        auto it = live_by_id.find(hd.id_hash);
        if (it != live_by_id.end()) {
          for (size_t i : it->second) dead[i] = true;
          live_by_id.erase(it);
        }
      } else if (hd.ttype_hash != 0) {
        auto it = rule_of.find(hd.event_hash);
        if (it != rule_of.end()) {
          live_by_id[hd.id_hash].push_back(matches.size());
          matches.push_back({hd.event_time_ms, off + (int64_t)kHeaderSize,
                             (int64_t)hd.payload_len, it->second});
          dead.push_back(false);
        }
      }
      off += hd.record_len;
    }
    std::vector<HeaderMatch> alive;
    alive.reserve(matches.size());
    for (size_t i = 0; i < matches.size(); i++)
      if (!dead[i]) alive.push_back(matches[i]);
    matches.swap(alive);
  }
  std::stable_sort(matches.begin(), matches.end(),
                   [](const HeaderMatch& a, const HeaderMatch& b) {
                     return a.time_ms != b.time_ms ? a.time_ms < b.time_ms
                                                   : a.off < b.off;
                   });

  // pass 2: payload parse + interning, in scan order
  res->users.reserve(matches.size());
  res->items.reserve(matches.size());
  res->vals.reserve(matches.size());
  ParsedEvent ev;
  std::string scratch;
  const size_t prop_len = prop_name ? strlen(prop_name) : 0;
  for (const auto& m : matches) {
    ev.reset();
    bool want_prop = value_is_prop[m.rule] != 0;
    if (!parse_event_payload((const char*)base + m.off, m.len,
                             want_prop ? prop_name : nullptr,
                             want_prop ? prop_len : 0, &ev, &scratch)) {
      (*out_bad)++;
      continue;
    }
    if (ev.event != names[(size_t)m.rule]) continue;  // hash collision
    if (!ev.has_target) continue;  // header said target; payload disagrees
    float v;
    if (want_prop) {
      if (!ev.has_prop) {
        (*out_bad)++;
        continue;
      }
      v = (float)ev.prop_val;
    } else {
      v = (float)fixed_vals[m.rule];
    }
    res->users.push_back(res->user_ix.index(ev.entity_id));
    res->items.push_back(res->item_ix.index(ev.target_id));
    res->vals.push_back(v);
  }
  munmap(map, (size_t)size);
  *out_n = (int64_t)res->users.size();
  return res;
}

int64_t evlog_ratings_n_users(void* vr) {
  return (int64_t)((RatingsResult*)vr)->user_ix.order.size();
}
int64_t evlog_ratings_n_items(void* vr) {
  return (int64_t)((RatingsResult*)vr)->item_ix.order.size();
}

// Copy the rating triples into caller-allocated arrays of length *out_n.
void evlog_ratings_fill(void* vr, int32_t* users, int32_t* items,
                        float* vals) {
  auto* r = (RatingsResult*)vr;
  memcpy(users, r->users.data(), r->users.size() * sizeof(int32_t));
  memcpy(items, r->items.data(), r->items.size() * sizeof(int32_t));
  memcpy(vals, r->vals.data(), r->vals.size() * sizeof(float));
}

// Unique-id pools: total byte length of all ids concatenated (no
// separators); fill writes the bytes plus per-id end offsets (int64[n]).
static int64_t pool_bytes(const Interner& ix) {
  int64_t total = 0;
  for (const auto& s : ix.order) total += (int64_t)s.size();
  return total;
}
static void pool_fill(const Interner& ix, uint8_t* buf, int64_t* ends) {
  int64_t off = 0;
  int64_t i = 0;
  for (const auto& s : ix.order) {
    memcpy(buf + off, s.data(), s.size());
    off += (int64_t)s.size();
    ends[i++] = off;
  }
}

int64_t evlog_ratings_user_pool_bytes(void* vr) {
  return pool_bytes(((RatingsResult*)vr)->user_ix);
}
int64_t evlog_ratings_item_pool_bytes(void* vr) {
  return pool_bytes(((RatingsResult*)vr)->item_ix);
}
void evlog_ratings_user_pool_fill(void* vr, uint8_t* buf, int64_t* ends) {
  pool_fill(((RatingsResult*)vr)->user_ix, buf, ends);
}
void evlog_ratings_item_pool_fill(void* vr, uint8_t* buf, int64_t* ends) {
  pool_fill(((RatingsResult*)vr)->item_ix, buf, ends);
}

void evlog_ratings_free(void* vr) { delete (RatingsResult*)vr; }

}  // extern "C"
