// Batch string hashing for the big-ID path (HashedIdMap).
//
// BiMap-style exact indexing holds every unique id in a host dict; at
// billions of ids that is a memory wall (SURVEY §7 flags it). The hashed
// path needs only a hash per id — this kernel hashes a whole chunk of ids
// (concatenated bytes + end offsets, the same pool layout ratings.cc uses)
// in one native call, threaded.
//
// Hash: fnv1a64 seeded with a caller salt (salt=0 reproduces the event
// log's evlog_fnv1a64 exactly).

#include <algorithm>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

inline uint64_t fnv1a64(const uint8_t* data, int64_t len, uint64_t salt) {
  uint64_t h = 14695981039346656037ull ^ salt;
  for (int64_t i = 0; i < len; i++) {
    h ^= (uint64_t)data[i];
    h *= 1099511628211ull;
  }
  return h ? h : 1;
}

}  // namespace

extern "C" {

// buf: concatenated UTF-8 ids; ends[i] = exclusive end offset of id i
// (id i spans [ends[i-1], ends[i])). Writes n hashes to out.
void pio_fnv1a64_batch(const uint8_t* buf, const int64_t* ends, int64_t n,
                       uint64_t salt, uint64_t* out) {
  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = hw == 0 ? 4 : (int)std::min(hw, 16u);
  if (n < 4096) nthreads = 1;
  const int64_t chunk = (n + nthreads - 1) / nthreads;
  std::vector<std::thread> ts;
  for (int t = 0; t < nthreads; ++t) {
    ts.emplace_back([&, t]() {
      const int64_t lo = t * chunk;
      const int64_t hi = std::min<int64_t>(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        int64_t start = i == 0 ? 0 : ends[i - 1];
        out[i] = fnv1a64(buf + start, ends[i] - start, salt);
      }
    });
  }
  for (auto& th : ts) th.join();
}

}  // extern "C"
