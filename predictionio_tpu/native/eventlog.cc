// Native append-only event log with mmap bulk scans.
//
// The storage-plane replacement for the reference's HBase events backend
// (data/src/main/scala/io/prediction/data/storage/hbase/: HBEventsUtil.scala
// row-key + scan push-down, HBLEvents.scala point ops, HBPEvents.scala bulk
// region scans). Where the reference pushes SingleColumnValueFilter/time-range
// predicates to regionservers (HBEventsUtil.scala:280-404), this log stores
// fixed 80-byte numeric headers per record and scans them with mmap at memory
// bandwidth; only records surviving the numeric prefilter have their JSON
// payload decoded by the Python layer (which also re-verifies exact string
// matches, so 64-bit hash collisions cannot produce wrong results for
// inserts; tombstone matching is hash-exact only).
//
// Record layout (little-endian, 8-byte aligned):
//   u32 record_len        total bytes incl. header, multiple of 8
//   u32 flags             bit0 = tombstone (delete marker)
//   i64 event_time_ms
//   i64 creation_time_ms
//   u64 etype_hash        fnv1a64(entityType)
//   u64 entity_hash       fnv1a64(entityType \0 entityId)
//   u64 event_hash        fnv1a64(event name)
//   u64 ttype_hash        fnv1a64(targetEntityType), 0 when no target
//   u64 target_hash       fnv1a64(targetType \0 targetId), 0 when no target
//   u64 id_hash           fnv1a64(event_id string)
//   u32 payload_len       JSON payload bytes (record_len - 80 >= payload_len)
//   u32 reserved
//   u8  payload[...]      UTF-8 JSON (the event's wire-format dict)
//
// A tombstone record carries the id_hash of the deleted event; it is always
// appended after the insert it deletes, so a single forward pass that
// collects candidate matches and the tombstone set, then filters, is exact.
//
// Concurrency: appends are serialized by a per-handle mutex within a
// process and an advisory flock(2) across processes (multiple handles on
// one log — the event server + `pio import` coexistence case). The lock
// makes the append's write(2) + rollback atomic with respect to other
// writers, and open-time torn-tail truncation can never clip a record
// another live process is mid-appending. Scans take no lock: they bound
// themselves to the last validated size, so a concurrent append is either
// fully visible or not yet scanned. Open truncates any torn tail left by a
// crashed process (under the same lock).
//
// Multi-writer scaling happens a level up (storage/native_events.py): N
// ingest processes each append to their own segment FILE of the same app
// (this library sees each segment as an independent log, so per-file flock
// is uncontended), and reads merge segments. The Python layer keeps the
// ordering invariant that makes merged tombstone filtering exact: segments
// hold only fresh-id inserts; tombstones and same-id re-inserts live in
// the primary log only (see evlog_tombstones below).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <fcntl.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include "eventlog_internal.h"

using pio::FileLock;
using pio::Handle;
using pio::kFlagTombstone;
using pio::kHeaderSize;
using pio::RecordHeader;
using pio::refresh_size;
using pio::validate_range;

namespace {

struct Match {
  int64_t time_ms;
  int64_t off;  // payload offset in file
  int64_t len;  // payload length
  uint64_t id_hash;
};

}  // namespace

extern "C" {

uint64_t evlog_fnv1a64(const uint8_t* data, int64_t len) {
  uint64_t h = 14695981039346656037ull;
  for (int64_t i = 0; i < len; i++) {
    h ^= (uint64_t)data[i];
    h *= 1099511628211ull;
  }
  return h ? h : 1;  // 0 is reserved for "absent / don't care"
}

void* evlog_open(const char* path) {
  int fd = open(path, O_RDWR | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return nullptr;
  // Exclusive lock: no other process is mid-append while we validate (and
  // possibly truncate) the tail, so an in-flight record can't be clipped.
  FileLock lock(fd);
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  auto* h = new Handle();
  h->fd = fd;
  h->path = path;
  if (!validate_range(fd, (int64_t)st.st_size, 0, &h->size, &h->n_records)) {
    // Could not inspect the file (mmap failure): refuse to open rather than
    // risk truncating valid data on a transient error.
    close(fd);
    delete h;
    return nullptr;
  }
  if (h->size < (int64_t)st.st_size) {
    // torn tail from a crash: drop it
    if (ftruncate(fd, (off_t)h->size) != 0) { /* keep going; scans use h->size */ }
  }
  return h;
}

void evlog_close(void* vh) {
  auto* h = (Handle*)vh;
  if (!h) return;
  if (h->fd >= 0) close(h->fd);
  delete h;
}

int64_t evlog_count(void* vh) { return ((Handle*)vh)->n_records; }
int64_t evlog_size(void* vh) { return ((Handle*)vh)->size; }

int evlog_sync(void* vh) {
  auto* h = (Handle*)vh;
  std::lock_guard<std::mutex> lock(h->mu);
  return fdatasync(h->fd) == 0 ? 0 : -errno;
}

namespace {

// Fill one record header (shared by single and batch append paths).
void fill_header(RecordHeader* hdr, uint32_t flags, int64_t event_time_ms,
                 int64_t creation_time_ms, uint64_t etype_hash,
                 uint64_t entity_hash, uint64_t event_hash,
                 uint64_t ttype_hash, uint64_t target_hash, uint64_t id_hash,
                 uint32_t payload_len) {
  memset(hdr, 0, sizeof(*hdr));
  hdr->record_len = kHeaderSize + ((payload_len + 7u) & ~7u);
  hdr->flags = flags;
  hdr->event_time_ms = event_time_ms;
  hdr->creation_time_ms = creation_time_ms;
  hdr->etype_hash = etype_hash;
  hdr->entity_hash = entity_hash;
  hdr->event_hash = event_hash;
  hdr->ttype_hash = ttype_hash;
  hdr->target_hash = target_hash;
  hdr->id_hash = id_hash;
  hdr->payload_len = payload_len;
}

// Append a pre-serialized run of n_new records under the handle mutex +
// advisory file lock: full-write-or-rollback, then fold any foreign
// appends into the handle's size/count accounting. Returns the file
// offset where the run begins, or -errno.
int64_t append_locked(Handle* h, const uint8_t* data, int64_t total,
                      int64_t n_new) {
  std::lock_guard<std::mutex> lock(h->mu);
  FileLock flock_guard(h->fd);  // serialize with other processes' appends
  ssize_t written = 0;
  while (written < (ssize_t)total) {
    ssize_t w = write(h->fd, data + written, (size_t)total - written);
    if (w <= 0) {
      int saved = errno ? errno : EIO;
      if (written > 0) {
        // Partial write: under the file lock no other writer can
        // interleave, so the last `written` bytes are exactly ours —
        // roll them back.
        struct stat st;
        if (fstat(h->fd, &st) == 0) {
          if (ftruncate(h->fd, (off_t)(st.st_size - written)) != 0) {
            /* scans remain bounded by validated sizes */
          }
        }
      }
      return -(int64_t)saved;
    }
    written += w;
  }
  // Our run ends at the current file end (O_APPEND). Fold in anything
  // other writers appended before us as well.
  struct stat st;
  if (fstat(h->fd, &st) != 0) {
    h->size += total;  // fallback: at least account for our own write
    h->n_records += n_new;
    return h->size - total;
  }
  int64_t end = (int64_t)st.st_size;
  if (end - total > h->size) {
    int64_t committed, count;
    if (validate_range(h->fd, end - total, h->size, &committed, &count)) {
      h->n_records += count;
    }
  }
  h->size = end;
  h->n_records += n_new;
  return end - total;
}

}  // namespace

// Append one record. Returns payload offset in file, or -errno.
int64_t evlog_append(void* vh, uint32_t flags, int64_t event_time_ms,
                     int64_t creation_time_ms, uint64_t etype_hash,
                     uint64_t entity_hash, uint64_t event_hash,
                     uint64_t ttype_hash, uint64_t target_hash,
                     uint64_t id_hash, const uint8_t* payload,
                     uint32_t payload_len) {
  auto* h = (Handle*)vh;
  uint32_t record_len = kHeaderSize + ((payload_len + 7u) & ~7u);
  std::vector<uint8_t> buf(record_len, 0);
  RecordHeader hdr;
  fill_header(&hdr, flags, event_time_ms, creation_time_ms, etype_hash,
              entity_hash, event_hash, ttype_hash, target_hash, id_hash,
              payload_len);
  memcpy(buf.data(), &hdr, kHeaderSize);
  if (payload_len) memcpy(buf.data() + kHeaderSize, payload, payload_len);
  int64_t start = append_locked(h, buf.data(), record_len, 1);
  if (start < 0) return start;
  return start + (int64_t)kHeaderSize;
}

// Append a batch of insert records under ONE lock acquisition and ONE
// write(2): the bulk-import fast path (`pio import`, PEvents.write parity —
// the reference batches via saveAsNewAPIHadoopDataset, HBPEvents.scala:
// 166-184). payload_blob holds all payloads concatenated; payload_ends[i]
// is the exclusive end offset of payload i. All records are plain inserts
// (flags=0). Returns the number appended (== n), or -errno; on a partial
// write the whole batch is rolled back (truncate under the lock), so the
// batch is atomic with respect to durability.
int64_t evlog_append_batch(void* vh, int64_t n, const int64_t* event_time_ms,
                           const int64_t* creation_time_ms,
                           const uint64_t* etype_hash,
                           const uint64_t* entity_hash,
                           const uint64_t* event_hash,
                           const uint64_t* ttype_hash,
                           const uint64_t* target_hash,
                           const uint64_t* id_hash,
                           const uint8_t* payload_blob,
                           const int64_t* payload_ends) {
  auto* h = (Handle*)vh;
  // serialize every record into one contiguous buffer
  int64_t total = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t start = i == 0 ? 0 : payload_ends[i - 1];
    uint32_t plen = (uint32_t)(payload_ends[i] - start);
    total += kHeaderSize + ((plen + 7u) & ~7u);
  }
  std::vector<uint8_t> buf((size_t)total, 0);
  int64_t off = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t start = i == 0 ? 0 : payload_ends[i - 1];
    uint32_t plen = (uint32_t)(payload_ends[i] - start);
    RecordHeader hdr;
    fill_header(&hdr, 0, event_time_ms[i], creation_time_ms[i],
                etype_hash[i], entity_hash[i], event_hash[i], ttype_hash[i],
                target_hash[i], id_hash[i], plen);
    memcpy(buf.data() + off, &hdr, kHeaderSize);
    if (plen) memcpy(buf.data() + off + kHeaderSize, payload_blob + start, plen);
    off += hdr.record_len;
  }

  int64_t start = append_locked(h, buf.data(), total, n);
  if (start < 0) return start;
  return n;
}

// Bulk scan with predicate push-down. Any hash argument of 0 means "any";
// start_ms/until_ms of INT64_MIN/INT64_MAX mean unbounded; has_target:
// -1 any, 0 must-have-no-target, 1 must-have-target. Matches are sorted by
// (event_time_ms, file offset) ascending. Returns the total number of
// matches; only the first `cap` (payload offset, payload len, event time
// ms, id hash) tuples are written to out_off/out_len/out_time/out_id
// (out_id may be null when the caller does not need cross-segment
// tombstone filtering). Call again with a larger cap if truncated.
int64_t evlog_scan(void* vh, int64_t start_ms, int64_t until_ms,
                   uint64_t etype_hash, uint64_t entity_hash,
                   const uint64_t* event_hashes, int32_t n_event_hashes,
                   uint64_t ttype_hash, uint64_t target_hash,
                   int32_t has_target, int64_t* out_off, int64_t* out_len,
                   int64_t* out_time, uint64_t* out_id, int64_t cap) {
  auto* h = (Handle*)vh;
  int64_t size;
  {
    std::lock_guard<std::mutex> lock(h->mu);
    refresh_size(h);
    size = h->size;
  }
  if (size < (int64_t)kHeaderSize) return 0;
  void* map = mmap(nullptr, (size_t)size, PROT_READ, MAP_SHARED, h->fd, 0);
  if (map == MAP_FAILED) return -(int64_t)errno;
  madvise(map, (size_t)size, MADV_SEQUENTIAL);
  const uint8_t* base = (const uint8_t*)map;

  std::unordered_set<uint64_t> ev_set;
  for (int32_t i = 0; i < n_event_hashes; i++) ev_set.insert(event_hashes[i]);
  // Order-sensitive tombstones: a delete marker only kills records appended
  // BEFORE it, so an id re-inserted after a delete stays live (matching the
  // upsert semantics of the SQLite backend). live_by_id tracks, per id_hash,
  // the indices of not-yet-killed matches.
  std::vector<Match> matches;
  std::vector<bool> dead_flags;
  std::unordered_map<uint64_t, std::vector<size_t>> live_by_id;

  int64_t off = 0;
  while (off + (int64_t)kHeaderSize <= size) {
    RecordHeader hd;
    memcpy(&hd, base + off, kHeaderSize);
    if (hd.record_len < kHeaderSize || off + (int64_t)hd.record_len > size)
      break;  // defensive; open() validated the tail
    if (hd.flags & kFlagTombstone) {
      auto it = live_by_id.find(hd.id_hash);
      if (it != live_by_id.end()) {
        for (size_t i : it->second) dead_flags[i] = true;
        live_by_id.erase(it);
      }
    } else {
      bool ok = hd.event_time_ms >= start_ms && hd.event_time_ms < until_ms;
      if (ok && etype_hash && hd.etype_hash != etype_hash) ok = false;
      if (ok && entity_hash && hd.entity_hash != entity_hash) ok = false;
      if (ok && n_event_hashes > 0 && !ev_set.count(hd.event_hash)) ok = false;
      if (ok && ttype_hash && hd.ttype_hash != ttype_hash) ok = false;
      if (ok && target_hash && hd.target_hash != target_hash) ok = false;
      if (ok && has_target == 0 && hd.ttype_hash != 0) ok = false;
      if (ok && has_target == 1 && hd.ttype_hash == 0) ok = false;
      if (ok) {
        live_by_id[hd.id_hash].push_back(matches.size());
        matches.push_back({hd.event_time_ms, off + (int64_t)kHeaderSize,
                           (int64_t)hd.payload_len, hd.id_hash});
        dead_flags.push_back(false);
      }
    }
    off += hd.record_len;
  }
  munmap(map, (size_t)size);

  {
    std::vector<Match> alive;
    alive.reserve(matches.size());
    for (size_t i = 0; i < matches.size(); i++) {
      if (!dead_flags[i]) alive.push_back(matches[i]);
    }
    matches.swap(alive);
  }
  std::stable_sort(matches.begin(), matches.end(),
                   [](const Match& a, const Match& b) {
                     return a.time_ms != b.time_ms ? a.time_ms < b.time_ms
                                                   : a.off < b.off;
                   });
  int64_t n = (int64_t)matches.size();
  int64_t write_n = std::min(n, cap);
  for (int64_t i = 0; i < write_n; i++) {
    out_off[i] = matches[i].off;
    out_len[i] = matches[i].len;
    out_time[i] = matches[i].time_ms;
    if (out_id) out_id[i] = matches[i].id_hash;
  }
  return n;
}

// All tombstone id hashes in the log (the primary log's delete/upsert
// markers). Multi-segment reads subtract this set from secondary-segment
// matches: segments hold only fresh-id inserts (ids that did not exist
// before being appended there and are never re-inserted there), so ANY
// tombstone for an id kills that id's segment records — no ordering
// needed across files. Returns the total count; fills up to cap.
int64_t evlog_tombstones(void* vh, uint64_t* out, int64_t cap) {
  auto* h = (Handle*)vh;
  int64_t size;
  {
    std::lock_guard<std::mutex> lock(h->mu);
    refresh_size(h);
    size = h->size;
  }
  if (size < (int64_t)kHeaderSize) return 0;
  void* map = mmap(nullptr, (size_t)size, PROT_READ, MAP_SHARED, h->fd, 0);
  if (map == MAP_FAILED) return -(int64_t)errno;
  madvise(map, (size_t)size, MADV_SEQUENTIAL);
  const uint8_t* base = (const uint8_t*)map;
  int64_t n = 0;
  int64_t off = 0;
  while (off + (int64_t)kHeaderSize <= size) {
    RecordHeader hd;
    memcpy(&hd, base + off, kHeaderSize);
    if (hd.record_len < kHeaderSize || off + (int64_t)hd.record_len > size)
      break;
    if (hd.flags & kFlagTombstone) {
      if (n < cap && out) out[n] = hd.id_hash;
      n++;
    }
    off += hd.record_len;
  }
  munmap(map, (size_t)size);
  return n;
}

// Latest record with the given id_hash. Returns 1 and fills
// out_off/out_len (payload) when the latest is a live record, -1 when the
// latest is a tombstone (deleted — multi-segment readers stop here rather
// than probing other segments), 0 when the id never appears.
int32_t evlog_get(void* vh, uint64_t id_hash, int64_t* out_off,
                  int64_t* out_len) {
  auto* h = (Handle*)vh;
  int64_t size;
  {
    std::lock_guard<std::mutex> lock(h->mu);
    refresh_size(h);
    size = h->size;
  }
  if (size < (int64_t)kHeaderSize) return 0;
  void* map = mmap(nullptr, (size_t)size, PROT_READ, MAP_SHARED, h->fd, 0);
  if (map == MAP_FAILED) return 0;
  const uint8_t* base = (const uint8_t*)map;
  int64_t found_off = -1, found_len = 0;
  bool dead = false, seen = false;
  int64_t off = 0;
  while (off + (int64_t)kHeaderSize <= size) {
    RecordHeader hd;
    memcpy(&hd, base + off, kHeaderSize);
    if (hd.record_len < kHeaderSize || off + (int64_t)hd.record_len > size)
      break;
    if (hd.id_hash == id_hash) {
      seen = true;
      if (hd.flags & kFlagTombstone) {
        dead = true;
      } else {
        found_off = off + (int64_t)kHeaderSize;
        found_len = (int64_t)hd.payload_len;
        dead = false;
      }
    }
    off += hd.record_len;
  }
  munmap(map, (size_t)size);
  if (!seen) return 0;
  if (found_off < 0 || dead) return -1;
  *out_off = found_off;
  *out_len = found_len;
  return 1;
}

}  // extern "C"
