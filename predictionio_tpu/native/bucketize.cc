// Degree-bucketed COO -> padded-CSR scatter (training infeed hot path).
//
// Native counterpart of the numpy bucketize in ops/als.py (same output
// contract, bit-identical arrays): the reference delegates this shaping to
// Spark MLlib's ALS block partitioner (inside ALS.train, invoked from e.g.
// examples/scala-parallel-recommendation/.../ALSAlgorithm.scala:56-62);
// here it is a two-pass threaded scatter:
//
//   pass A: per-thread row-degree histograms over disjoint nnz ranges
//   prefix: per-(thread,row) write bases so every element's slot is a pure
//           function of (thread, arrival order) -> fully parallel AND
//           deterministic pass B (no atomics, no sort)
//   pass B: scatter cols/vals straight into the caller-allocated padded
//           bucket slabs; elements beyond a row's bucket width are dropped
//           (same truncation rule as the numpy path)
//
// The validity mask is NOT materialized here: it is a pure function of the
// per-row count (prefix-form by construction), which the Python side keeps
// as a [B] int32 array and the device solve re-expands for free. Column
// indices write as uint16 when the opposite-side id space fits (halves the
// largest slab's bytes both in host fill and host->device transfer).
//
// The numpy path costs an O(nnz log nnz) argsort; this is O(nnz) with
// sequential writes per thread in pass A and per-row locality in pass B.
//
// Python allocates all outputs (numpy owns the memory); this file only
// fills them. Buckets and slot assignments are computed in numpy (cheap,
// O(n_rows)) and passed down.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Pass-B scatter body, instantiated per idx element type. `base` carries
// the per-(thread,row) write offsets computed by the histogram prefix.
template <class IdxT>
void scatter_range(const int32_t* rows, const int32_t* cols,
                   const float* vals, int64_t lo, int64_t hi,
                   std::vector<int32_t>& base, const int32_t* bucket_of,
                   const int32_t* slot_of, const int32_t* widths,
                   void** idx_ptrs, float** val_ptrs) {
  for (int64_t k = lo; k < hi; ++k) {
    const int32_t r = rows[k];
    const int32_t w = base[static_cast<size_t>(r)]++;
    const int32_t b = bucket_of[r];
    const int32_t width = widths[b];
    if (w >= width) continue;  // truncated tail of an over-wide row
    const int64_t off = static_cast<int64_t>(slot_of[r]) * width + w;
    static_cast<IdxT*>(idx_ptrs[b])[off] = static_cast<IdxT>(cols[k]);
    val_ptrs[b][off] = vals[k];
  }
}

int hardware_threads(int64_t n_rows) {
  unsigned n = std::thread::hardware_concurrency();
  int t = n == 0 ? 4 : static_cast<int>(n > 16 ? 16 : n);
  // Pass A allocates one n_rows int32 histogram per thread; bound the
  // total at ~512 MB so huge row spaces degrade to fewer threads instead
  // of O(n_rows x threads) memory blow-up.
  const int64_t budget = 512ll << 20;
  int64_t per_thread = n_rows * 4;
  if (per_thread > 0 && per_thread * t > budget) {
    t = static_cast<int>(std::max<int64_t>(1, budget / per_thread));
  }
  return t;
}

}  // namespace

extern "C" {

// rows/cols: [nnz] int32, vals: [nnz] float32.
// bucket_of: [n_rows] int32 -- bucket index per row id (every row with
//   degree > 0 has one; rows absent from the data never appear in `rows`).
// slot_of: [n_rows] int32 -- row's position within its bucket.
// widths: [n_buckets] int32.
// idx_ptrs/val_ptrs: [n_buckets] pointers to zero-initialized slabs of
//   shape [B_b * widths[b]] (uint16 when idx_u16 else int32 / float32).
// idx_u16: nonzero when column ids fit uint16 and the idx slabs are
//   uint16 (caller guarantees max col id <= 0xFFFF).
// Returns 0 on success.
int pio_bucketize_fill(const int32_t* rows, const int32_t* cols,
                       const float* vals, int64_t nnz, int64_t n_rows,
                       const int32_t* bucket_of, const int32_t* slot_of,
                       const int32_t* widths, int32_t n_buckets,
                       void** idx_ptrs, float** val_ptrs, int32_t idx_u16) {
  (void)n_buckets;
  const int nthreads = hardware_threads(n_rows);
  const int64_t chunk = (nnz + nthreads - 1) / nthreads;

  // pass A: per-thread degree histograms over [t*chunk, (t+1)*chunk)
  std::vector<std::vector<int32_t>> hist(static_cast<size_t>(nthreads));
  {
    std::vector<std::thread> ts;
    ts.reserve(static_cast<size_t>(nthreads));
    for (int t = 0; t < nthreads; ++t) {
      ts.emplace_back([&, t]() {
        auto& h = hist[static_cast<size_t>(t)];
        h.assign(static_cast<size_t>(n_rows), 0);
        const int64_t lo = t * chunk;
        const int64_t hi = std::min<int64_t>(nnz, lo + chunk);
        for (int64_t k = lo; k < hi; ++k) ++h[static_cast<size_t>(rows[k])];
      });
    }
    for (auto& th : ts) th.join();
  }

  // prefix over threads: hist[t][r] becomes the within-row write base for
  // thread t (number of row-r elements in threads < t)
  for (int64_t r = 0; r < n_rows; ++r) {
    int32_t acc = 0;
    for (int t = 0; t < nthreads; ++t) {
      int32_t c = hist[static_cast<size_t>(t)][static_cast<size_t>(r)];
      hist[static_cast<size_t>(t)][static_cast<size_t>(r)] = acc;
      acc += c;
    }
  }

  // pass B: deterministic parallel scatter into the padded slabs
  {
    std::vector<std::thread> ts;
    ts.reserve(static_cast<size_t>(nthreads));
    for (int t = 0; t < nthreads; ++t) {
      ts.emplace_back([&, t]() {
        auto& base = hist[static_cast<size_t>(t)];
        const int64_t lo = t * chunk;
        const int64_t hi = std::min<int64_t>(nnz, lo + chunk);
        if (idx_u16) {
          scatter_range<uint16_t>(rows, cols, vals, lo, hi, base,
                                  bucket_of, slot_of, widths, idx_ptrs,
                                  val_ptrs);
        } else {
          scatter_range<int32_t>(rows, cols, vals, lo, hi, base,
                                 bucket_of, slot_of, widths, idx_ptrs,
                                 val_ptrs);
        }
      });
    }
    for (auto& th : ts) th.join();
  }
  return 0;
}

}  // extern "C"
