"""Native (C++) runtime components.

The reference keeps all native-performance code behind JVM dependencies
(SURVEY §2.9: Spark/netlib, HBase client, netty — no in-tree C++). Here the
framework owns its native runtime: sources in this package are compiled
on demand with the system toolchain into per-ABI shared libraries and loaded
via ctypes — no pybind11 dependency.

Build artifacts land in ``_build/`` next to the sources and are rebuilt
whenever a source file's SHA-1 changes (stamp file per library).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()
_CACHE = {}

#: Every native component: library name → source list (None = <name>.cc).
#: Single source of truth shared by the runtime load sites and `pio build`'s
#: ahead-of-time compile, so the precompile can never drift stale.
LIBRARIES = {
    "eventlog": ["eventlog.cc", "ratings.cc"],
    "bucketize": None,
    "idhash": None,
}


class NativeBuildError(RuntimeError):
    """Compilation of a native component failed."""


def _source_digest(sources) -> str:
    sha = hashlib.sha1()
    # Headers are not compile inputs but must invalidate the stamp.
    headers = sorted(
        os.path.join(_HERE, f) for f in os.listdir(_HERE) if f.endswith(".h")
    )
    for src in list(sources) + headers:
        with open(src, "rb") as f:
            sha.update(f.read())
    return sha.hexdigest()


def build_library(name: str, sources=None, extra_flags=()) -> str:
    """Compile a library from ``LIBRARIES[name]`` (or explicit sources)
    into ``_build/lib<name>.so`` if missing or stale. Returns the path.

    The .so is written to a temp name and renamed into place, so a
    concurrent process (e.g. ``pio build`` racing a lazily-compiling
    server) can never dlopen a half-written file."""
    if sources is None:
        sources = LIBRARIES.get(name) or [f"{name}.cc"]
    sources = [os.path.join(_HERE, s) for s in sources]
    os.makedirs(_BUILD_DIR, exist_ok=True)
    lib_path = os.path.join(_BUILD_DIR, f"lib{name}.so")
    stamp_path = os.path.join(_BUILD_DIR, f"lib{name}.stamp")
    digest = _source_digest(sources)
    if os.path.exists(lib_path) and os.path.exists(stamp_path):
        with open(stamp_path) as f:
            if f.read().strip() == digest:
                return lib_path
    cxx = os.environ.get("CXX", "g++")
    tmp_path = f"{lib_path}.tmp.{os.getpid()}"
    cmd = [
        cxx, "-O2", "-shared", "-fPIC", "-std=c++17",
        "-Wall", "-Wextra",
        *extra_flags, "-o", tmp_path, *sources,
    ]
    try:
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True)
        except (FileNotFoundError, OSError) as exc:
            # no compiler on PATH (or it can't exec) — the same "native
            # unavailable" condition as a failed compile, so callers'
            # single NativeBuildError fallback covers it
            raise NativeBuildError(f"cannot run {cxx!r}: {exc}") from exc
        if proc.returncode != 0:
            raise NativeBuildError(
                f"building {name} failed ({' '.join(cmd)}):\n{proc.stderr}"
            )
        # fsync the compiler's output before renaming it into place: the
        # build cache is checked by a stamp file, so a power loss that
        # tears the .so under its final name would never trigger a
        # rebuild — every later process would dlopen garbage
        from ..utils.durability import fsync_dir, fsync_file

        fsync_file(tmp_path)
        os.replace(tmp_path, lib_path)  # atomic: readers see old or new
        fsync_dir(_BUILD_DIR)
    finally:
        # interrupt / late failure: never leak the pid-suffixed temp
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
    with open(stamp_path, "w") as f:
        f.write(digest)
    return lib_path


def load_library(name: str, sources=None) -> ctypes.CDLL:
    """Build (if needed) and dlopen a native component, cached per process."""
    with _LOCK:
        if name not in _CACHE:
            try:
                # pio: lint-ok[robust-unbounded-cache, flow-blocking-under-lock] keys are a closed set of in-tree component names, and _LOCK exists precisely to serialize the one-time compile — blocking under it is the point
                _CACHE[name] = ctypes.CDLL(build_library(name, sources))
            except NativeBuildError:
                raise
            except OSError as exc:  # dlopen failure
                raise NativeBuildError(
                    f"loading lib{name}.so failed: {exc}"
                ) from exc
        return _CACHE[name]
