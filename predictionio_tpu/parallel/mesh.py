"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's Spark executor topology
(``workflow/WorkflowContext.scala:78-97`` created a SparkContext; here a
train/eval/serving run gets a ``jax.sharding.Mesh``). Axes follow the
scaling-book convention:

- ``data``  — batch/data parallelism (the analogue of RDD partitions);
- ``model`` — tensor/factor sharding (the analogue of MLlib ALS blocks).

Collectives ride ICI within a slice; multi-slice meshes put ``data``
outermost so cross-slice traffic (DCN) carries only gradient/Gramian
reductions.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Mesh shape request. ``axes`` maps axis name → size; a size of -1 means
    "all remaining devices" (at most one axis may be -1)."""

    axes: Tuple[Tuple[str, int], ...] = ((DATA_AXIS, -1),)

    @staticmethod
    def from_dict(d: Dict[str, int]) -> "MeshConfig":
        return MeshConfig(tuple(d.items()))


def create_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    Single-device environments yield a 1-device mesh with the same axis
    names, so all sharding annotations stay valid from laptop CPU to a pod
    slice (compile-once, shard-anywhere).
    """
    config = config or MeshConfig()
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)

    names = [name for name, _ in config.axes]
    sizes = [size for _, size in config.axes]
    wild = [i for i, s in enumerate(sizes) if s == -1]
    if len(wild) > 1:
        raise ValueError("At most one mesh axis may be -1")
    fixed = math.prod(s for s in sizes if s != -1)
    if wild:
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by fixed axes product {fixed}"
            )
        sizes[wild[0]] = n // fixed
    elif math.prod(sizes) != n:
        raise ValueError(
            f"Mesh axes {dict(config.axes)} need {math.prod(sizes)} devices, "
            f"have {n}"
        )
    grid = np.array(devs).reshape(sizes)
    return Mesh(grid, tuple(names))


def data_sharding(mesh: Mesh, *, axis: str = DATA_AXIS) -> NamedSharding:
    """Leading dim sharded over the data axis (batch parallelism)."""
    return NamedSharding(mesh, P(axis))

def model_sharding(mesh: Mesh, *, axis: str = MODEL_AXIS) -> NamedSharding:
    """Leading dim sharded over the model axis (factor-table sharding)."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated — the analogue of the reference's broadcast "L"
    models (``Algorithm.scala:118-145``)."""
    return NamedSharding(mesh, P())


def slice_mesh(mesh: Mesh, n: int, *, axis: str = DATA_AXIS) -> list:
    """Split a mesh into up to ``n`` independent submeshes along ``axis``.

    The hyperparameter-sweep device topology (SURVEY §2.8 row 5): each
    EngineParams candidate trains on its own slice, so a 4-way sweep on an
    8-device mesh runs 4 concurrent 2-device trainings instead of 4
    sequential 8-device ones. Returns as many slices as the axis actually
    divides into (>= 1); every slice keeps the full axis-name set so all
    sharding annotations stay valid on the smaller mesh.
    """
    if n <= 1:
        return [mesh]
    axis_names = list(mesh.axis_names)
    if axis not in axis_names:  # nothing to slice along — run shared
        return [mesh]
    axis_idx = axis_names.index(axis)
    devs = np.asarray(mesh.devices)
    size = devs.shape[axis_idx]
    n = min(n, size)
    while size % n != 0:  # only even splits keep static shapes
        n -= 1
    if n <= 1:
        return [mesh]
    return [
        Mesh(chunk, tuple(axis_names))
        for chunk in np.split(devs, n, axis=axis_idx)
    ]


def shard_batch(mesh: Mesh, array, *, axis: str = DATA_AXIS):
    """Pad the leading dim to a multiple of the axis size and device_put with
    batch sharding. Returns (sharded_array, original_length)."""
    import jax.numpy as jnp

    n = array.shape[0]
    per = mesh.shape[axis]
    padded = ((n + per - 1) // per) * per
    if padded != n:
        pad_width = [(0, padded - n)] + [(0, 0)] * (array.ndim - 1)
        array = np.pad(np.asarray(array), pad_width)
    return jax.device_put(array, data_sharding(mesh, axis=axis)), n
