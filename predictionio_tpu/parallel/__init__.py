"""Parallelism: mesh construction, shardings, collective helpers (SURVEY §2.8)."""

from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshConfig,
    create_mesh,
    data_sharding,
    model_sharding,
    replicated,
    shard_batch,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "MeshConfig",
    "create_mesh",
    "data_sharding",
    "model_sharding",
    "replicated",
    "shard_batch",
]
