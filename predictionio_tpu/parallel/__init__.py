"""Parallelism: mesh construction, shardings, collectives, multi-host init
(SURVEY §2.8, §5 "Distributed communication backend")."""

from .collectives import (
    all_gather_rows,
    all_reduce_sum,
    reduce_scatter_rows,
    ring_shift,
    sharded_matmul_allreduce,
)
from .distributed import hybrid_mesh, initialize_from_env, process_info
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshConfig,
    create_mesh,
    data_sharding,
    model_sharding,
    replicated,
    shard_batch,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "MeshConfig",
    "all_gather_rows",
    "all_reduce_sum",
    "create_mesh",
    "data_sharding",
    "hybrid_mesh",
    "initialize_from_env",
    "model_sharding",
    "process_info",
    "reduce_scatter_rows",
    "replicated",
    "ring_shift",
    "shard_batch",
    "sharded_matmul_allreduce",
]
