"""Multi-host (multi-process) runtime initialization.

The reference scales out by launching Spark executors on a cluster (the
``spark-submit`` boundary, ``RunWorkflow.scala:103-169``); the TPU-native
analogue is one JAX process per host of a pod slice, joined through
``jax.distributed``. Configuration is env-driven like the rest of the
framework (SURVEY §5 config tiers):

- ``PIO_DIST_COORDINATOR``   — ``host:port`` of process 0 (presence turns
  multi-process mode on)
- ``PIO_DIST_NUM_PROCESSES`` — world size
- ``PIO_DIST_PROCESS_ID``    — this process's rank
- ``PIO_DIST_HEARTBEAT_S``   — coordination-service heartbeat timeout
  (default 100): a dead peer is detected within this bound and every
  surviving process fails LOUDLY instead of hanging in a collective —
  the failure-detection half of the SURVEY §5 "fail loud, resume from
  checkpoint" contract (the recovery half is workflow/checkpoint.py)

On TPU pods these usually come from the platform and plain
``jax.distributed.initialize()`` autodetects them; the env vars are the
explicit override path (self-managed clusters, CPU simulation).

``hybrid_mesh`` builds the ICI×DCN mesh for multi-slice jobs: axes listed in
``dcn_axes`` cross slice boundaries (data-parallel outermost, per the
scaling-book recipe — only gradient/Gramian reductions ride DCN), everything
else stays inside a slice on ICI.
"""

from __future__ import annotations

import inspect
import os
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def initialize_from_env(env: Optional[Dict[str, str]] = None) -> bool:
    """Join the multi-process runtime when configured; no-op otherwise.

    Returns True when running multi-process (after initialization).
    Idempotent: repeated calls are safe.

    ``PIO_DIST_HEARTBEAT_S`` is forwarded as
    ``heartbeat_timeout_seconds`` only on jax versions whose
    ``jax.distributed.initialize`` accepts it — the kwarg came and went
    across releases, and passing it blindly made *every* multi-process
    start raise ``TypeError`` before a single collective ran (the root
    cause of both distributed seed-test failures, ROUND6_NOTES.md).
    Where unsupported, peer-death detection falls back to the
    coordination service's own timeouts.
    """
    e = env if env is not None else os.environ
    coordinator = e.get("PIO_DIST_COORDINATOR")
    if not coordinator:
        return False
    if getattr(initialize_from_env, "_initialized", False):
        return True
    num = int(e.get("PIO_DIST_NUM_PROCESSES", "1"))
    pid = int(e.get("PIO_DIST_PROCESS_ID", "0"))
    kwargs = dict(
        coordinator_address=coordinator,
        num_processes=num,
        process_id=pid,
    )
    try:
        params = inspect.signature(jax.distributed.initialize).parameters
    except (TypeError, ValueError):  # C accelerated / exotic wrappers
        params = {}
    if "heartbeat_timeout_seconds" in params:
        kwargs["heartbeat_timeout_seconds"] = int(
            e.get("PIO_DIST_HEARTBEAT_S", "100")
        )
    jax.distributed.initialize(**kwargs)
    initialize_from_env._initialized = True
    return True


def process_info() -> Tuple[int, int]:
    """(process_index, process_count) — (0, 1) in single-process mode."""
    return jax.process_index(), jax.process_count()


def hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Mesh spanning slices: ``dcn_axes`` (outermost) cross slice boundaries
    over DCN, ``ici_axes`` stay within a slice on ICI.

    Single-slice (or CPU-simulated) environments collapse to a plain mesh
    with the same axis names, so sharding annotations written against a
    hybrid mesh run anywhere.
    """
    from jax.experimental import mesh_utils

    dcn_axes = dcn_axes or {}
    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    dcn_shape = tuple(dcn_axes.values())
    ici_shape = tuple(ici_axes.values())
    n_needed = int(np.prod(dcn_shape + ici_shape, dtype=np.int64))
    devices = jax.devices()
    if len(devices) < n_needed:
        raise ValueError(
            f"hybrid mesh needs {n_needed} devices, have {len(devices)}"
        )
    if dcn_shape and jax.process_count() > 1:
        # create_hybrid_device_mesh wants same-rank per-axis shape pairs
        # (elementwise product per axis): DCN axes get 1 on the ICI side and
        # vice versa, so axis i spans dcn_i * ici_i devices. On TPU pods the
        # DCN granule is the slice (devices carry slice_index); everywhere
        # else (CPU simulation, single-slice-per-host clusters) the granule
        # is the process.
        distinct_slices = {
            getattr(d, "slice_index", None) for d in devices[:n_needed]
        }
        has_slices = None not in distinct_slices and len(distinct_slices) > 1
        mesh_shape = (1,) * len(dcn_shape) + ici_shape
        dcn_mesh_shape = dcn_shape + (1,) * len(ici_shape)
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape,
            dcn_mesh_shape,
            devices=devices[:n_needed],
            process_is_granule=not has_slices,
        )
        return Mesh(grid, names)
    grid = np.array(devices[:n_needed]).reshape(dcn_shape + ici_shape)
    return Mesh(grid, names)
