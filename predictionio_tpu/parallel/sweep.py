"""Sweep execution over mesh slices.

The reference evaluates a hyperparameter grid with a parallel collection
(``MetricEvaluator.scala:202-211``); the TPU-native analogue runs each
candidate on its own mesh slice (SURVEY §2.8 row 5). This module owns the
scheduling so ``Engine.batch_eval`` and ``FastEvalEngine.batch_eval``
share one implementation:

- :class:`SlicePool` — a checkout pool of slice contexts. Tasks acquire a
  FREE slice (not a submission-index-mapped one), so when candidates
  outnumber slices a finishing slice is immediately reused and no two
  concurrent trainings ever contend for the same devices.
- :func:`run_sliced` — ordered map of tasks over the pool.
"""

from __future__ import annotations

import contextlib
import queue
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Sequence

__all__ = ["SlicePool", "run_sliced"]


class SlicePool:
    """Checkout pool over a context's mesh slices."""

    def __init__(self, ctx, parallelism: int):
        slices = ctx.slices(parallelism) if hasattr(ctx, "slices") else [ctx]
        self._free: "queue.Queue" = queue.Queue()
        for s in slices:
            self._free.put(s)
        self.n_slices = len(slices)

    @contextlib.contextmanager
    def acquire(self):
        """Check out a slice context; blocks until one is free. Never nest
        acquisitions on the same pool from within a held slice — with all
        slices held by waiting parents that deadlocks."""
        ctx = self._free.get()
        try:
            yield ctx
        finally:
            self._free.put(ctx)


def run_sliced(
    ctx,
    tasks: Sequence[Callable[[Any], Any]],
    parallelism: int,
) -> List[Any]:
    """Run ``tasks`` (each a callable taking a slice context) concurrently,
    one free slice per running task; returns results in task order. The
    first task exception propagates (after all tasks settle)."""
    pool = SlicePool(ctx, parallelism)

    def run(task):
        with pool.acquire() as sliced:
            return task(sliced)

    with ThreadPoolExecutor(
        max_workers=pool.n_slices, thread_name_prefix="sweep"
    ) as executor:
        futs = [executor.submit(run, t) for t in tasks]
        return [f.result() for f in futs]
