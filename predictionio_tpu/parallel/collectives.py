"""Collective-communication surface.

The reference has **zero in-tree collective code** — all inter-node traffic
rides Spark shuffle / akka RPC behind the ``RDD`` boundary (SURVEY §2.8,
§5 "Distributed communication backend"). The TPU-native equivalent is XLA
collectives over ICI/DCN, expressed here as explicit, user-callable wrappers
over ``jax.lax`` primitives inside ``shard_map``. Framework code (sharded
aggregation, ring attention, sweep reduction) builds on these; inside plain
``pjit`` programs XLA inserts the same collectives automatically from
sharding annotations — these helpers are for the cases where the schedule
must be explicit (rings, manual reductions).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # jax >= 0.6: shard_map is a top-level API (check_vma kwarg)
    from jax import shard_map
except ImportError:  # older jax: experimental location, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(f, /, *, check_vma=True, **kwargs):
        return _shard_map_compat(f, check_rep=check_vma, **kwargs)

from jax.sharding import Mesh, PartitionSpec as P


def all_reduce_sum(x, mesh: Mesh, axis: str):
    """Sum ``x``'s per-device shards (leading dim sharded over ``axis``) —
    the ``psum`` analogue of the reference's ``aggregateByKey`` merges
    (``PEventAggregator.scala:198-203``). Returns the replicated sum of the
    per-shard slices."""
    f = shard_map(
        lambda s: jax.lax.psum(s, axis),
        mesh=mesh,
        in_specs=P(axis, *([None] * (x.ndim - 1))),
        out_specs=P(*([None] * x.ndim)),
    )
    return jax.jit(f)(x)


def all_gather_rows(x, mesh: Mesh, axis: str):
    """Gather row-shards of ``x`` onto every device (replicated result)."""
    f = shard_map(
        lambda s: jax.lax.all_gather(s, axis, tiled=True),
        mesh=mesh,
        in_specs=P(axis, *([None] * (x.ndim - 1))),
        out_specs=P(*([None] * x.ndim)),
        # the gathered result IS replicated; the static VMA check just can't
        # prove it through all_gather
        check_vma=False,
    )
    return jax.jit(f)(x)


def reduce_scatter_rows(x, mesh: Mesh, axis: str):
    """Sum a replicated array across devices, leaving each device 1/Nth of
    the rows (``reduce_scatter`` over ICI)."""
    f = shard_map(
        lambda s: jax.lax.psum_scatter(s, axis, tiled=True),
        mesh=mesh,
        in_specs=P(*([None] * x.ndim)),
        out_specs=P(axis, *([None] * (x.ndim - 1))),
    )
    return jax.jit(f)(x)


def ring_shift(x, mesh: Mesh, axis: str, shift: int = 1):
    """Rotate row-shards around the ``axis`` ring by ``shift`` positions
    (``ppermute`` — the building block of ring attention / pipelined
    exchanges). Shard i's rows end up on shard (i + shift) mod N."""
    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]
    f = shard_map(
        lambda s: jax.lax.ppermute(s, axis, perm),
        mesh=mesh,
        in_specs=P(axis, *([None] * (x.ndim - 1))),
        out_specs=P(axis, *([None] * (x.ndim - 1))),
    )
    return jax.jit(f)(x)


def sharded_matmul_allreduce(a, b, mesh: Mesh, axis: str):
    """Contraction-dim-sharded matmul with ICI all-reduce: ``a [M, K/N]`` ×
    ``b [K/N, P]`` per device, psum of partial products — the canonical
    "model-parallel matmul" schedule from the scaling-book recipe."""
    f = shard_map(
        lambda sa, sb: jax.lax.psum(
            jnp.einsum("mk,kp->mp", sa, sb,
                       preferred_element_type=jnp.float32),
            axis,
        ),
        mesh=mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(None, None),
    )
    return jax.jit(f)(a, b)
