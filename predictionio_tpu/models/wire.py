"""Shared wire-shape helpers for template results.

Every recommender-style template serves the reference's camelCase
``itemScores`` JSON (``{"itemScores": [{"item": ..., "score": ...}]}``);
each template keeps its own ``ItemScore``/``PredictedResult`` types (the
reference's per-template Engine.scala isolation) but renders through this
one function so the wire shape cannot drift between templates.
"""

from __future__ import annotations

from typing import Iterable


def item_scores_json(scores: Iterable) -> dict:
    return {
        "itemScores": [
            {"item": s.item, "score": s.score} for s in scores
        ]
    }
