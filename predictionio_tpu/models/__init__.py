"""Engine templates — the workloads of SURVEY §2.6, rebuilt TPU-native."""

from .recommendation import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    ALSModel,
    ItemScore,
    PredictedResult,
    Query,
    RecDataSource,
    RecDataSourceParams,
    RecPreparator,
)
from .recommendation import engine_factory as recommendation_engine_factory

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "ALSModel",
    "ItemScore",
    "PredictedResult",
    "Query",
    "RecDataSource",
    "RecDataSourceParams",
    "RecPreparator",
    "recommendation_engine_factory",
]
