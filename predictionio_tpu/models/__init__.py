"""Engine templates — the workloads of SURVEY §2.6, rebuilt TPU-native."""

from . import classification, ecommerce, recommendation, similarproduct
from .classification import engine_factory as classification_engine_factory
from .ecommerce import engine_factory as ecommerce_engine_factory
from .recommendation import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    ALSModel,
    ItemScore,
    PredictedResult,
    Query,
    RecDataSource,
    RecDataSourceParams,
    RecPreparator,
)
from .recommendation import engine_factory as recommendation_engine_factory
from .similarproduct import engine_factory as similarproduct_engine_factory

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "ALSModel",
    "ItemScore",
    "PredictedResult",
    "Query",
    "RecDataSource",
    "RecDataSourceParams",
    "RecPreparator",
    "classification",
    "classification_engine_factory",
    "ecommerce",
    "ecommerce_engine_factory",
    "recommendation",
    "recommendation_engine_factory",
    "similarproduct",
    "similarproduct_engine_factory",
]
