"""E-commerce recommendation engine template.

Rebuild of ``examples/scala-parallel-ecommercerecommendation/
train-with-rate-event/src/main/scala/``: a P2L-style ALS whose predict
applies live business filters at query time —

- explicit ALS over rate events, keeping the LATEST rating per (user, item)
  (``ALSAlgorithm.scala:82-117``);
- seen-items filter from the user's live event stream when ``unseenOnly``
  (``ALSAlgorithm.scala:160-192``);
- "unavailableItems" constraint read from the latest ``$set`` on the
  ``constraint/unavailableItems`` entity (``ALSAlgorithm.scala:195-215``);
- known user → factor dot-products; unknown user → cosine similarity against
  the user's 10 most recent viewed items (``predictNewUser``,
  ``ALSAlgorithm.scala:284-360``).

The reference bounds each live read with a 200 ms timeout
(``Duration(200, "millis")``); here the same budget guards the host-side
event-store reads so the device scoring path never blocks on storage.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    Params,
)
from ..ops.als import ALSConfig, als_train_coo
from ..storage import BiMap, EventFilter, get_registry
from .similarproduct import (
    Item,
    ItemScore,
    PredictedResult,
    build_category_members,
    category_allowed_mask,
)

logger = logging.getLogger(__name__)

#: Live event-read budget (seconds) — the template's 200 ms Duration.
LIVE_READ_TIMEOUT_S = 0.2


@dataclasses.dataclass(frozen=True)
class Query:
    """``Query(user, num, categories, whiteList, blackList)``."""

    user: str
    num: int = 10
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass
class RateEvent:
    user: str
    item: str
    rating: float
    t: int


@dataclasses.dataclass
class TrainingData:
    users: Dict[str, None]
    items: Dict[str, Item]
    rate_events: List[RateEvent]

    def sanity_check(self) -> None:
        if not self.rate_events:
            raise ValueError("ecommerce TrainingData has no rate events")


@dataclasses.dataclass(frozen=True)
class ECommerceDataSourceParams(Params):
    app_id: int = 1


class ECommerceDataSource(DataSource):
    """``$set`` user/item entities + rate events (template DataSource)."""

    params_class = ECommerceDataSourceParams

    def __init__(
        self, params: ECommerceDataSourceParams = ECommerceDataSourceParams()
    ):
        self.params = params

    def read_training(self, ctx) -> TrainingData:
        store = get_registry().get_events()
        app_id = self.params.app_id
        users = {
            uid: None
            for uid in store.aggregate_properties(app_id, "user").keys()
        }
        items = {
            iid: Item(categories=tuple(props.get("categories") or ()))
            for iid, props in store.aggregate_properties(app_id, "item").items()
        }
        rates: List[RateEvent] = []
        for e in store.find(
            app_id, EventFilter(entity_type="user", event_names=["rate"])
        ):
            if e.target_entity_id is None:
                continue
            rates.append(
                RateEvent(
                    user=e.entity_id,
                    item=e.target_entity_id,
                    rating=float(e.properties.get("rating")),
                    t=int(e.event_time.timestamp() * 1000),
                )
            )
        return TrainingData(users=users, items=items, rate_events=rates)


@dataclasses.dataclass(frozen=True)
class ECommerceALSParams(Params):
    """``ALSAlgorithmParams(appId, unseenOnly, seenEvents, rank,
    numIterations, lambda, seed)``."""

    app_id: int = 1
    unseen_only: bool = True
    seen_events: Tuple[str, ...] = ("buy", "view")
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: int = 3


@dataclasses.dataclass
class ECommerceModel:
    """Collected factor tables + id maps (``ALSModel``,
    ``ALSAlgorithm.scala:30-51``) — the P2L pattern: distributed train,
    host/HBM-resident serving tables."""

    rank: int
    user_factors: np.ndarray  # [U, R]
    item_factors: np.ndarray  # [I, R]
    user_map: BiMap
    item_map: BiMap
    items: Dict[int, Item]

    def sanity_check(self) -> None:
        if not np.isfinite(self.user_factors).all():
            raise ValueError("ECommerceModel user factors are non-finite")

    @functools.cached_property
    def category_members(self) -> Dict[str, np.ndarray]:
        """category → member index arrays (shared builder, see
        ``similarproduct.build_category_members``), built once per model
        instance; excluded from pickling — recomputed after load."""
        return build_category_members(self.items)

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("category_members", None)
        return state


class ECommerceALSAlgorithm(Algorithm):
    """Explicit ALS + live-filtered serving (``ALSAlgorithm.scala``)."""

    params_class = ECommerceALSParams

    def __init__(self, params: ECommerceALSParams = ECommerceALSParams()):
        self.params = params

    # -- train (ALSAlgorithm.scala:64-146) --------------------------------
    def train(self, ctx, pd: TrainingData) -> ECommerceModel:
        if not pd.rate_events:
            raise ValueError("rateEvents cannot be empty")
        if not pd.users or not pd.items:
            raise ValueError("users/items cannot be empty")
        user_map = BiMap.string_int(pd.users.keys())
        item_map = BiMap.string_int(pd.items.keys())
        # latest rating per (user, item) wins
        latest: Dict[Tuple[int, int], RateEvent] = {}
        for r in pd.rate_events:
            u, i = user_map.get(r.user), item_map.get(r.item)
            if u is None or i is None:
                logger.info(
                    "Skipping rate event with unknown ids %s->%s", r.user, r.item
                )
                continue
            key = (u, i)
            if key not in latest or r.t > latest[key].t:
                latest[key] = r
        if not latest:
            raise ValueError("no valid rate events after id mapping")
        users = np.array([k[0] for k in latest], np.int64)
        items = np.array([k[1] for k in latest], np.int64)
        vals = np.array([e.rating for e in latest.values()], np.float32)
        factors = als_train_coo(
            users,
            items,
            vals,
            n_users=len(user_map),
            n_items=len(item_map),
            cfg=ALSConfig(
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                lambda_=self.params.lambda_,
                implicit_prefs=False,
                seed=self.params.seed,
            ),
        )
        return ECommerceModel(
            rank=self.params.rank,
            user_factors=np.asarray(factors.user_factors),
            item_factors=np.asarray(factors.item_factors),
            user_map=user_map,
            item_map=item_map,
            items={item_map[i]: item for i, item in pd.items.items()},
        )

    # -- live filters (ALSAlgorithm.scala:160-215) ------------------------
    def _seen_items(self, user: str) -> Set[str]:
        if not self.params.unseen_only:
            return set()
        try:
            store = get_registry().get_events()
            deadline = time.monotonic() + LIVE_READ_TIMEOUT_S
            seen: Set[str] = set()
            for e in store.find_single_entity(
                self.params.app_id,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.seen_events),
                target_entity_type="item",
            ):
                if e.target_entity_id is not None:
                    seen.add(e.target_entity_id)
                if time.monotonic() > deadline:
                    logger.error("Timeout reading seen events for %s", user)
                    break
            return seen
        except Exception as exc:
            logger.error("Error when read seen events: %s", exc)
            return set()

    def _unavailable_items(self) -> Set[str]:
        try:
            store = get_registry().get_events()
            events = list(
                store.find_single_entity(
                    self.params.app_id,
                    entity_type="constraint",
                    entity_id="unavailableItems",
                    event_names=["$set"],
                    limit=1,
                    latest=True,
                )
            )
            if events:
                return set(events[0].properties.get("items") or ())
            return set()
        except Exception as exc:
            logger.error("Error when read set unavailableItems event: %s", exc)
            return set()

    # -- predict (ALSAlgorithm.scala:148-281) -----------------------------
    def predict(self, model: ECommerceModel, query: Query) -> PredictedResult:
        black = set(query.black_list or ())
        final_black = black | self._seen_items(query.user) | self._unavailable_items()
        black_idx = {
            model.item_map.get(x)
            for x in final_black
            if model.item_map.get(x) is not None
        }
        white_idx: Optional[Set[int]] = None
        if query.white_list is not None:
            white_idx = {
                model.item_map.get(x)
                for x in query.white_list
                if model.item_map.get(x) is not None
            }

        uidx = model.user_map.get(query.user)
        if uidx is not None:
            scores = model.item_factors @ model.user_factors[uidx]
        else:
            # new user: cosine against recent views (predictNewUser)
            logger.info("No userFeature found for user %s", query.user)
            recent = self._recent_view_items(query.user)
            recent_idx = [
                model.item_map.get(x)
                for x in recent
                if model.item_map.get(x) is not None
            ]
            if not recent_idx:
                return PredictedResult(item_scores=())
            f = model.item_factors
            unit = f / np.maximum(np.linalg.norm(f, axis=1, keepdims=True), 1e-12)
            scores = unit @ unit[recent_idx].sum(axis=0)

        excluded = np.zeros((model.item_factors.shape[0],), bool)
        excluded[list(black_idx)] = True
        if white_idx is not None:
            mask = np.ones_like(excluded)
            mask[list(white_idx)] = False
            excluded |= mask
        if query.categories is not None:
            # vectorized via the model's precomputed category index arrays
            excluded |= ~category_allowed_mask(
                model.category_members, query.categories,
                excluded.shape[0],
            )

        scores = np.where(excluded | (scores <= 0), -np.inf, scores)
        k = min(query.num, int(np.isfinite(scores).sum()))
        if k <= 0:
            return PredictedResult(item_scores=())
        top = np.argpartition(-scores, k - 1)[:k]
        top = top[np.argsort(-scores[top])]
        inv = model.item_map.inverse
        return PredictedResult(
            item_scores=tuple(
                ItemScore(item=inv[int(i)], score=float(scores[i]))
                for i in top
                if np.isfinite(scores[i])
            )
        )

    def _recent_view_items(self, user: str) -> List[str]:
        """Latest 10 viewed items (``predictNewUser``,
        ``ALSAlgorithm.scala:294-323``)."""
        try:
            store = get_registry().get_events()
            return [
                e.target_entity_id
                for e in store.find_single_entity(
                    self.params.app_id,
                    entity_type="user",
                    entity_id=user,
                    event_names=["view"],
                    target_entity_type="item",
                    limit=10,
                    latest=True,
                )
                if e.target_entity_id is not None
            ]
        except Exception as exc:
            logger.error("Error when read recent events: %s", exc)
            return []

    def query_class(self):
        return Query


def engine_factory() -> Engine:
    """``ECommerceRecommendationEngine`` (template ``Engine.scala``)."""
    return Engine(
        {"": ECommerceDataSource},
        {"": IdentityPreparator},
        {"als": ECommerceALSAlgorithm},
        {"": FirstServing},
    )
