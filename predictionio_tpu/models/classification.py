"""Classification engine template (NaiveBayes + RandomForest ensemble).

Rebuild of ``examples/scala-parallel-classification/add-algorithm/src/main/
scala/``: the DataSource derives labeled points from
``aggregateProperties`` over "user" entities with required properties
``plan, attr0, attr1, attr2`` (``DataSource.scala:27-56``); the engine maps
two algorithms — ``"naive"`` (MLlib ``NaiveBayes.train`` with ``lambda``,
``NaiveBayesAlgorithm.scala:19-27``) and ``"randomforest"``
(``RandomForestAlgorithm.scala:28-49``) — combined by a first-prediction
Serving (``Serving.scala:5-12``, ``Engine.scala:15-23``).

TPU restatement: both algorithms train on device via the sufficient-statistic
/ histogram kernels in :mod:`predictionio_tpu.ops.classifier` and
:mod:`predictionio_tpu.ops.forest`.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np

from ..controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    Params,
)
from ..ops import classifier, forest
from ..storage import get_registry


@dataclasses.dataclass(frozen=True)
class Query:
    """``Query(features)`` (``Engine.scala:6-8``)."""

    features: Tuple[float, ...]

    def __post_init__(self):
        object.__setattr__(self, "features", tuple(self.features))


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    """``PredictedResult(label)`` (``Engine.scala:10-12``)."""

    label: float


@dataclasses.dataclass
class TrainingData:
    """Labeled points (``DataSource.scala:59-61``).

    ``entity_ids`` is row-aligned provenance: which entity each labeled
    point was aggregated from. The continuous controller's fold path
    needs it to translate a delta batch's entity ids into rows; eval
    folds and hand-built fixtures may leave it ``None`` (fold-in then
    refuses and the controller escalates to a full retrain).
    """

    features: np.ndarray  # [N, D]
    labels: np.ndarray  # [N]
    entity_ids: Tuple[str, ...] = None  # [N] source entity per row

    def sanity_check(self) -> None:
        if self.features.shape[0] == 0:
            raise ValueError("Classification TrainingData is empty")
        if not np.isfinite(self.features).all():
            raise ValueError("Classification features contain non-finite values")


@dataclasses.dataclass(frozen=True)
class ClassificationDataSourceParams(Params):
    app_id: int = 1
    entity_type: str = "user"
    label_property: str = "plan"
    feature_properties: Tuple[str, ...] = ("attr0", "attr1", "attr2")
    eval_k: int = 0  # >0 enables k-fold readEval


class ClassificationDataSource(DataSource):
    """``aggregateProperties`` → labeled points (``DataSource.scala:27-56``);
    entities missing a required property are skipped (the reference's
    ``required=...`` filter)."""

    params_class = ClassificationDataSourceParams

    def __init__(
        self,
        params: ClassificationDataSourceParams = ClassificationDataSourceParams(),
    ):
        self.params = params

    def read_training(self, ctx) -> TrainingData:
        p = self.params
        store = get_registry().get_events()
        required = (p.label_property,) + tuple(p.feature_properties)
        props_by_entity = store.aggregate_properties(
            p.app_id, p.entity_type, required=required
        )
        feats: List[List[float]] = []
        labels: List[float] = []
        entity_ids: List[str] = []
        for entity_id, props in sorted(props_by_entity.items()):
            entity_ids.append(entity_id)
            labels.append(float(props.get(p.label_property)))
            feats.append([float(props.get(f)) for f in p.feature_properties])
        return TrainingData(
            features=np.asarray(feats, np.float32).reshape(
                len(labels), len(p.feature_properties)
            ),
            labels=np.asarray(labels),
            entity_ids=tuple(entity_ids),
        )

    def read_eval(self, ctx):
        td = self.read_training(ctx)
        k = max(2, self.params.eval_k)
        folds = []
        idx = np.arange(td.labels.shape[0])
        for f in range(k):
            test = idx % k == f
            train_td = TrainingData(
                features=td.features[~test],
                labels=td.labels[~test],
                entity_ids=(
                    tuple(np.asarray(td.entity_ids, object)[~test])
                    if td.entity_ids is not None
                    else None
                ),
            )
            qa = [
                (
                    Query(features=tuple(td.features[i])),
                    PredictedResult(label=float(td.labels[i])),
                )
                for i in idx[test]
            ]
            folds.append((train_td, None, qa))
        return folds


@dataclasses.dataclass(frozen=True)
class NaiveBayesParams(Params):
    """``NaiveBayesAlgorithmParams(lambda)``."""

    lam: float = 1.0


@dataclasses.dataclass
class NaiveBayesModel:
    """The ops-layer NB model plus the engine-generic fold surface.

    The continuous controller's fold protocol is duck-typed: any model
    exposing ``user_map``/``item_map`` (entity id → row) paired with an
    algorithm exposing ``fold_in``/``fold_in_supported`` rides the same
    decide → fold → persist loop ALS does — the controller itself has no
    per-template code. Classification has one entity axis, so
    ``item_map`` is always empty; ``user_map`` values are the training
    rows the entities came from (membership is the contract the
    controller reads, the indices are this model's provenance only).
    """

    nb: classifier.MultinomialNBModel
    user_map: dict  # entity id -> training row
    item_map: dict  # no second entity axis: always {}

    def predict(self, features) -> float:
        return self.nb.predict(features)

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        return self.nb.predict_batch(features)

    def sanity_check(self) -> None:
        self.nb.sanity_check()


class NaiveBayesAlgorithm(Algorithm):
    """Multinomial NB on device (``NaiveBayesAlgorithm.scala:19-27``)."""

    params_class = NaiveBayesParams

    def __init__(self, params: NaiveBayesParams = NaiveBayesParams()):
        self.params = params

    def train(self, ctx, pd: TrainingData) -> NaiveBayesModel:
        nb = classifier.train(pd.features, pd.labels, lam=self.params.lam)
        ents = pd.entity_ids if getattr(pd, "entity_ids", None) else ()
        return NaiveBayesModel(
            nb=nb,
            user_map={e: i for i, e in enumerate(ents)},
            item_map={},
        )

    @property
    def fold_in_supported(self) -> bool:
        """Multinomial NB's sufficient statistics are additive, so
        folding new labeled entities is EXACT (identical to a retrain on
        the union) — the cheapest possible fold path."""
        return True

    def fold_in(
        self,
        ctx,
        model: NaiveBayesModel,
        pd: TrainingData,
        changed_user_ids,
        changed_item_ids,
        policy=None,
    ):
        """Fold changed/new entities' labeled points into the model by
        adding their scatter-add statistics (:func:`classifier.fold_in`).
        New entities are exact; a re-``$set`` entity is approximate (its
        old row still contributes) — the controller's RMSE-drift gate
        judges that. Returns ``(NaiveBayesModel, FoldInStats)`` where the
        "rmse" fields carry the classification analogue: full-data error
        rate before/after the fold.
        """
        from ..continuous.foldin import FoldInStats

        if getattr(pd, "entity_ids", None) is None:
            raise ValueError(
                "prepared data has no entity_ids; cannot map the delta "
                "batch to labeled rows — full retrain instead"
            )
        row_of = {e: i for i, e in enumerate(pd.entity_ids)}
        # classification has one entity axis: fold whatever axis the
        # delta names (the controller passes both verbatim)
        changed = [
            e
            for e in dict.fromkeys(
                tuple(changed_user_ids) + tuple(changed_item_ids)
            )
            if e in row_of
        ]
        new = [e for e in changed if e not in model.user_map]
        rows = np.asarray([row_of[e] for e in changed], dtype=np.int64)
        before = self._error_rate(model.nb, pd)
        nb = (
            classifier.fold_in(model.nb, pd.features[rows], pd.labels[rows])
            if len(rows)
            else model.nb
        )
        after = self._error_rate(nb, pd)
        user_map = dict(model.user_map)
        for e in new:
            user_map[e] = row_of[e]
        folded = NaiveBayesModel(
            nb=nb, user_map=user_map, item_map=dict(model.item_map)
        )
        stats = FoldInStats(
            folded_users=len(rows),
            folded_items=0,
            new_users=len(new),
            new_items=0,
            rmse_before=before,
            rmse_after=after,
        )
        return folded, stats

    @staticmethod
    def _error_rate(nb: classifier.MultinomialNBModel, pd: TrainingData) -> float:
        """Full-data misclassification rate — the drift measure the fold
        policy's ``max_rmse_drift`` gates on for this template."""
        if pd.labels.shape[0] == 0:
            return 0.0
        pred = nb.predict_batch(np.asarray(pd.features, np.float32))
        return float(np.mean(pred != np.asarray(pd.labels)))

    def predict(self, model, query: Query) -> PredictedResult:
        return PredictedResult(label=model.predict(query.features))

    def batch_predict(self, model, indexed_queries):
        idx = [i for i, _ in indexed_queries]
        feats = np.asarray([q.features for _, q in indexed_queries], np.float32)
        labels = model.predict_batch(feats)
        return [
            (i, PredictedResult(label=float(l))) for i, l in zip(idx, labels)
        ]

    def query_class(self):
        return Query


@dataclasses.dataclass(frozen=True)
class RandomForestParams(Params):
    """``RandomForestAlgorithmParams`` (``RandomForestAlgorithm.scala:12-19``)."""

    num_classes: int = 2
    num_trees: int = 10
    feature_subset_strategy: str = "auto"
    impurity: str = "gini"
    max_depth: int = 4
    max_bins: int = 32
    seed: int = 0


class RandomForestAlgorithm(Algorithm):
    """Histogram random forest on device
    (``RandomForestAlgorithm.scala:28-49``)."""

    params_class = RandomForestParams

    def __init__(self, params: RandomForestParams = RandomForestParams()):
        self.params = params

    def train(self, ctx, pd: TrainingData) -> forest.RandomForestModel:
        p = self.params
        return forest.train(
            pd.features,
            pd.labels,
            forest.ForestConfig(
                num_classes=p.num_classes,
                num_trees=p.num_trees,
                feature_subset_strategy=p.feature_subset_strategy,
                impurity=p.impurity,
                max_depth=p.max_depth,
                max_bins=p.max_bins,
                seed=p.seed,
            ),
        )

    def predict(self, model, query: Query) -> PredictedResult:
        return PredictedResult(label=model.predict(query.features))

    def batch_predict(self, model, indexed_queries):
        idx = [i for i, _ in indexed_queries]
        feats = np.asarray([q.features for _, q in indexed_queries], np.float32)
        labels = model.predict_batch(feats)
        return [
            (i, PredictedResult(label=float(l))) for i, l in zip(idx, labels)
        ]

    def query_class(self):
        return Query


def engine_factory() -> Engine:
    """``ClassificationEngine`` (``Engine.scala:14-23``)."""
    return Engine(
        {"": ClassificationDataSource},
        {"": IdentityPreparator},
        {"naive": NaiveBayesAlgorithm, "randomforest": RandomForestAlgorithm},
        {"": FirstServing},
    )
