"""Sequence-recommendation engine: transformer next-item prediction.

The long-context upgrade of the reference's sequence machinery: where
briandamage/PredictionIO offers only a first-order ``MarkovChain`` over item
transitions (``e2/src/main/scala/io/prediction/e2/engine/MarkovChain.scala``),
this engine models whole interaction histories with a causal transformer —
same DASE shape as every other template (DataSource reads view/buy events,
Preparator indexes items and builds windows, Algorithm trains, Serving
answers ``queries.json``), but the context window is a first-class scaling
axis: attention dispatches to ring or Ulysses sequence parallelism over the
mesh ``seq`` axis for histories too long for one chip
(:mod:`predictionio_tpu.ops.attention`).

The transformer is deliberately framework-light (pure jax + optax pytrees,
pre-LN blocks, tied input/output embeddings) so the model pytree persists
through the standard model store like any other template's model.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Params,
    Preparator,
)
from ..ops.attention import attention
from ..storage import BiMap, EventFilter, get_registry


# -- query / result ---------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Query:
    """Next-item query: by user history (``user``) or explicit recent items."""

    user: Optional[str] = None
    recent_items: Tuple[str, ...] = ()
    num: int = 10


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def to_json_dict(self) -> dict:
        # same camelCase wire shape as the recommender templates
        from .wire import item_scores_json

        return item_scores_json(self.item_scores)


# -- training data ----------------------------------------------------------
@dataclasses.dataclass
class TrainingData:
    """Per-user, time-ordered item-id sequences."""

    user_ids: List[str]
    sequences: List[List[str]]

    def sanity_check(self):
        if not self.sequences:
            raise ValueError("No interaction sequences found; check app id "
                             "and event names.")


@dataclasses.dataclass
class PreparedData:
    item_map: BiMap
    windows: np.ndarray  # [W, seq_len + 1] int32, PAD = len(item_map)
    user_recent: Dict[str, List[int]]  # tail of each user's history
    seq_len: int

    @property
    def pad_id(self) -> int:
        return len(self.item_map)


# -- DASE components --------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class SeqDataSourceParams(Params):
    app_id: int = 1
    event_names: Tuple[str, ...] = ("view", "buy")


class SeqDataSource(DataSource):
    """Orders each user's view/buy events by event time into one sequence."""

    params_class = SeqDataSourceParams

    def __init__(self, params: SeqDataSourceParams = SeqDataSourceParams()):
        self.params = params

    def read_training(self, ctx) -> TrainingData:
        store = get_registry().get_events()
        cols = store.scan_columnar(
            self.params.app_id,
            EventFilter(event_names=list(self.params.event_names)),
        )
        by_user: Dict[str, List[Tuple[int, str]]] = {}
        for uid, tid, tms in zip(
            cols["entity_id"], cols["target_entity_id"],
            cols["event_time_ms"].tolist(),
        ):
            if tid is None:
                continue
            by_user.setdefault(uid, []).append((tms, tid))
        users, seqs = [], []
        for uid, pairs in by_user.items():
            pairs.sort(key=lambda p: p[0])
            users.append(uid)
            seqs.append([tid for _, tid in pairs])
        return TrainingData(user_ids=users, sequences=seqs)

    def read_eval(self, ctx):
        """Leave-one-out: last item of each ≥2-length sequence is the label."""
        td = self.read_training(ctx)
        train_seqs, qa = [], []
        users = []
        for uid, seq in zip(td.user_ids, td.sequences):
            if len(seq) >= 2:
                train_seqs.append(seq[:-1])
                users.append(uid)
                qa.append(
                    (Query(recent_items=tuple(seq[:-1]), num=10),
                     ItemScore(item=seq[-1], score=1.0))
                )
            else:
                train_seqs.append(seq)
                users.append(uid)
        return [(TrainingData(user_ids=users, sequences=train_seqs), None, qa)]


@dataclasses.dataclass(frozen=True)
class SeqPreparatorParams(Params):
    seq_len: int = 64
    #: slide stride when a history is longer than seq_len + 1
    window_stride: int = 32


class SeqPreparator(Preparator):
    """Item indexing + fixed-shape training windows (ragged histories become
    left-padded ``[W, seq_len+1]`` blocks — the static-shape layout XLA
    needs, same move as the ALS degree buckets)."""

    params_class = SeqPreparatorParams

    def __init__(self, params: SeqPreparatorParams = SeqPreparatorParams()):
        self.params = params

    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        L = self.params.seq_len
        item_map = BiMap.string_int(
            [i for seq in td.sequences for i in seq]
        )
        pad = len(item_map)
        windows: List[np.ndarray] = []
        user_recent: Dict[str, List[int]] = {}
        for uid, seq in zip(td.user_ids, td.sequences):
            idx = [item_map[i] for i in seq]
            user_recent[uid] = idx[-L:]
            if len(idx) < 2:
                continue
            span = L + 1
            starts = list(range(0, max(1, len(idx) - span + 1),
                                self.params.window_stride))
            # anchor a final window on the newest interactions — a stride
            # that doesn't divide the history must not drop the tail
            if len(idx) > span and starts[-1] != len(idx) - span:
                starts.append(len(idx) - span)
            for s in starts:
                w = idx[s : s + span]
                if len(w) < span:
                    w = [pad] * (span - len(w)) + w
                windows.append(np.asarray(w, dtype=np.int32))
        if not windows:
            raise ValueError("No training windows (all histories length < 2)")
        return PreparedData(
            item_map=item_map,
            windows=np.stack(windows),
            user_recent=user_recent,
            seq_len=L,
        )


# -- transformer ------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class SeqRecAlgorithmParams(Params):
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    steps: int = 300
    batch_size: int = 64
    learning_rate: float = 1e-3
    seed: int = 0
    #: attention schedule: "flash" (single device), "ring", "ulysses",
    #: or "auto" (ring when the ctx mesh has a seq axis of size > 1)
    schedule: str = "flash"
    #: attention implementation on the single-device path: "xla"
    #: (default) or "pallas" (fused flash kernel,
    #: ops.attention.flash_attention_pallas; EXPERIMENTAL until
    #: hardware-validated — flash_pallas step in the revalidation queue)
    flash_impl: str = "xla"


def _init_params(
    rng: np.random.Generator, vocab: int, p: SeqRecAlgorithmParams,
    max_positions: int,
):
    d = p.d_model

    def w(*shape, scale=None):
        scale = scale if scale is not None else 1.0 / np.sqrt(shape[0])
        return (rng.normal(size=shape) * scale).astype(np.float32)

    layers = []
    for _ in range(p.n_layers):
        layers.append({
            "ln1_g": np.ones(d, np.float32), "ln1_b": np.zeros(d, np.float32),
            "qkv": w(d, 3 * d), "proj": w(d, d),
            "ln2_g": np.ones(d, np.float32), "ln2_b": np.zeros(d, np.float32),
            "mlp_in": w(d, 4 * d), "mlp_out": w(4 * d, d),
        })
    return {
        "embed": w(vocab, d, scale=0.02),
        # sized to the training context (pd.seq_len): no silent cap
        "pos": w(max_positions, d, scale=0.02),
        "layers": layers,
        "lnf_g": np.ones(d, np.float32), "lnf_b": np.zeros(d, np.float32),
    }


def _layer_norm(x, g, b):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * g + b


def forward(params, tokens, n_heads: int, mesh=None, schedule: str = "flash",
            flash_impl: str = "xla"):
    """Causal LM forward: tokens [B, L] int32 → logits [B, L, V]."""
    b, l = tokens.shape
    d = params["embed"].shape[1]
    max_pos = params["pos"].shape[0]
    if l > max_pos:
        raise ValueError(
            f"sequence length {l} exceeds the model's positional table "
            f"({max_pos} positions — trained with a shorter seq_len)"
        )
    h = params["embed"][tokens] + params["pos"][:l][None]
    dh = d // n_heads
    for layer in params["layers"]:
        x = _layer_norm(h, layer["ln1_g"], layer["ln1_b"])
        qkv = x @ layer["qkv"]  # [B, L, 3D]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, l, n_heads, dh).transpose(0, 2, 1, 3)

        o = attention(
            heads(q), heads(k), heads(v),
            mesh=mesh if schedule in ("ring", "ulysses", "auto") else None,
            causal=True,
            schedule=schedule if schedule != "flash" else "auto",
            impl=flash_impl,
        )
        o = o.transpose(0, 2, 1, 3).reshape(b, l, d)
        h = h + o @ layer["proj"]
        x = _layer_norm(h, layer["ln2_g"], layer["ln2_b"])
        h = h + jax.nn.gelu(x @ layer["mlp_in"]) @ layer["mlp_out"]
    h = _layer_norm(h, params["lnf_g"], params["lnf_b"])
    return h @ params["embed"].T  # tied softmax


@dataclasses.dataclass
class SeqRecModel:
    """Trained transformer + id maps + per-user recent histories."""

    params: dict  # numpy pytree
    item_map: BiMap
    user_recent: Dict[str, List[int]]
    seq_len: int
    n_heads: int

    def sanity_check(self):
        flat, _ = jax.tree_util.tree_flatten(self.params)
        for leaf in flat:
            if not np.isfinite(np.asarray(leaf)).all():
                raise ValueError("sequencerec produced non-finite weights")

    def device_params(self):
        """Device-resident weight pytree, uploaded once per model — serving
        must not pay a full host→device weight transfer per query."""
        cache = self.__dict__.get("_device_params")
        if cache is None:
            cache = jax.tree_util.tree_map(jnp.asarray, self.params)
            self.__dict__["_device_params"] = cache
        return cache

    def __getstate__(self):
        # never pickle the device cache (model blobs stay pure numpy)
        state = dict(self.__dict__)
        state.pop("_device_params", None)
        return state


class SeqRecAlgorithm(Algorithm):
    """Causal-transformer next-item trainer (optax AdamW)."""

    params_class = SeqRecAlgorithmParams

    def __init__(self, params: SeqRecAlgorithmParams = SeqRecAlgorithmParams()):
        self.params = params

    def train(self, ctx, pd: PreparedData) -> SeqRecModel:
        import optax

        p = self.params
        vocab = len(pd.item_map) + 1  # + PAD
        pad_id = pd.pad_id
        rng = np.random.default_rng(p.seed)
        model_params = jax.tree_util.tree_map(
            jnp.asarray, _init_params(rng, vocab, p, max_positions=pd.seq_len)
        )
        mesh = ctx.mesh if (ctx is not None and p.schedule != "flash") else None

        opt = optax.adamw(p.learning_rate)
        opt_state = opt.init(model_params)

        def loss_fn(mp, batch):
            inp, tgt = batch[:, :-1], batch[:, 1:]
            logits = forward(mp, inp, p.n_heads, mesh, p.schedule,
                             flash_impl=p.flash_impl)
            mask = (tgt != pad_id).astype(jnp.float32)
            ll = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
            return (ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        @jax.jit
        def step(mp, os_, batch):
            loss, grads = jax.value_and_grad(loss_fn)(mp, batch)
            updates, os_ = opt.update(grads, os_, mp)
            return optax.apply_updates(mp, updates), os_, loss

        n = pd.windows.shape[0]
        for i in range(p.steps):
            take = rng.integers(0, n, size=min(p.batch_size, n))
            batch = jnp.asarray(pd.windows[take])
            model_params, opt_state, loss = step(model_params, opt_state, batch)
        return SeqRecModel(
            params=jax.tree_util.tree_map(np.asarray, model_params),
            item_map=pd.item_map,
            user_recent=pd.user_recent,
            seq_len=pd.seq_len,
            n_heads=p.n_heads,
        )

    # -- serving ----------------------------------------------------------
    def _tokens_for(self, model: SeqRecModel, query: Query) -> Optional[List[int]]:
        if query.recent_items:
            idx = [
                model.item_map[i]
                for i in query.recent_items
                if model.item_map.get(i) is not None
            ]
            return idx[-model.seq_len:] or None
        if query.user is not None:
            return model.user_recent.get(query.user)
        return None

    def predict(self, model: SeqRecModel, query: Query) -> PredictedResult:
        recent = self._tokens_for(model, query)
        if not recent:
            return PredictedResult(item_scores=())
        pad_id = len(model.item_map)
        # left-pad to the training context length: one compiled shape for
        # every query (the serving-cache move the scoring kernels also make)
        seq = [pad_id] * (model.seq_len - len(recent)) + list(recent)
        tokens = jnp.asarray(np.asarray(seq, np.int32)[None, :], jnp.int32)
        logits = forward(
            model.device_params(), tokens, model.n_heads,
            flash_impl=self.params.flash_impl,
        )[0, -1]
        # Next-item prediction keeps previously-seen items eligible (Markov
        # semantics: the next state may be a revisit) — only PAD is masked.
        # Top-k on device: no full-catalog sort on the serving hot path.
        k = min(query.num, len(model.item_map))
        scores = jax.nn.log_softmax(logits).at[pad_id].set(-jnp.inf)
        top_s, top_i = jax.lax.top_k(scores, k)
        top_s, top_i = jax.device_get((top_s, top_i))  # one round trip
        return PredictedResult(
            item_scores=tuple(
                ItemScore(item=model.item_map.inverse[int(i)], score=float(s))
                for s, i in zip(top_s, top_i)
                if np.isfinite(s)
            )
        )

    def query_class(self):
        return Query


def engine_factory() -> Engine:
    """EngineFactory for the sequence-recommendation template."""
    return Engine(
        {"": SeqDataSource},
        {"": SeqPreparator},
        {"transformer": SeqRecAlgorithm, "": SeqRecAlgorithm},
        {"": FirstServing},
    )
