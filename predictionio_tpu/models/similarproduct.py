"""Similar-product engine template (multi-algorithm ensemble).

Rebuild of ``examples/scala-parallel-similarproduct/multi/src/main/scala/``:

- DataSource reads ``$set`` user/item entities (items carry ``categories``),
  "view" events and "like"/"dislike" events (``DataSource.scala``);
- ``ALSAlgorithm`` trains implicit ALS over deduplicated view counts and
  scores similarity as summed cosine between query-item factors and all item
  factors (``ALSAlgorithm.scala:76-205``);
- ``LikeAlgorithm`` re-trains on like/dislike (latest event per (user, item)
  wins; like→1, dislike→−1) (``LikeAlgorithm.scala:17-90``);
- Serving z-score-standardizes each algorithm's scores (skipped when
  ``num == 1``) and sums per item (``Serving.scala:14-53``).

TPU restatement: both algorithms share the ALS kernel
(:mod:`predictionio_tpu.ops.als`, implicit mode); predict is one device
matvec over unit-normalized factor tables
(:func:`predictionio_tpu.ops.scoring.top_k_for_vectors`); the ensemble
standardization is :func:`predictionio_tpu.ops.scoring.standardize`.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..controller import (
    Algorithm,
    DataSource,
    Engine,
    IdentityPreparator,
    Params,
    Serving,
)
from ..ops.als import ALSConfig, als_train_coo
from ..ops.scoring import pad_pow2, top_k_for_vectors, use_streaming_topk
from ..storage import BiMap, EventFilter, get_registry


@dataclasses.dataclass(frozen=True)
class Item:
    """``Item(categories)`` (template's DataSource)."""

    categories: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Query:
    """``Query(items, num, categories, whiteList, blackList)``."""

    items: Tuple[str, ...]
    num: int = 10
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None

    def __post_init__(self):
        object.__setattr__(self, "items", tuple(self.items))


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...]

    def to_json_dict(self) -> dict:
        # reference wire shape: camelCase itemScores
        # (examples/scala-parallel-similarproduct Engine.scala)
        from .wire import item_scores_json

        return item_scores_json(self.item_scores)


@dataclasses.dataclass
class ViewEvent:
    user: str
    item: str
    t: int  # millis


@dataclasses.dataclass
class LikeEvent:
    user: str
    item: str
    t: int
    like: bool


@dataclasses.dataclass
class TrainingData:
    users: Dict[str, None]
    items: Dict[str, Item]
    view_events: List[ViewEvent]
    like_events: List[LikeEvent]

    def sanity_check(self) -> None:
        if not self.items:
            raise ValueError("similarproduct TrainingData has no items")


@dataclasses.dataclass(frozen=True)
class SimilarProductDataSourceParams(Params):
    app_id: int = 1


class SimilarProductDataSource(DataSource):
    """``$set`` entities + view + like/dislike streams
    (multi ``DataSource.scala``)."""

    params_class = SimilarProductDataSourceParams

    def __init__(
        self,
        params: SimilarProductDataSourceParams = SimilarProductDataSourceParams(),
    ):
        self.params = params

    def read_training(self, ctx) -> TrainingData:
        store = get_registry().get_events()
        app_id = self.params.app_id
        users = {
            uid: None
            for uid in store.aggregate_properties(app_id, "user").keys()
        }
        items = {
            iid: Item(categories=tuple(props.get("categories") or ()))
            for iid, props in store.aggregate_properties(app_id, "item").items()
        }
        views: List[ViewEvent] = []
        likes: List[LikeEvent] = []
        for e in store.find(
            app_id,
            EventFilter(
                entity_type="user",
                event_names=["view", "like", "dislike"],
            ),
        ):
            if e.target_entity_id is None:
                continue
            t = int(e.event_time.timestamp() * 1000)
            if e.event == "view":
                views.append(ViewEvent(e.entity_id, e.target_entity_id, t))
            else:
                likes.append(
                    LikeEvent(
                        e.entity_id, e.target_entity_id, t, e.event == "like"
                    )
                )
        return TrainingData(
            users=users, items=items, view_events=views, like_events=likes
        )


@dataclasses.dataclass(frozen=True)
class SimilarALSParams(Params):
    """``ALSAlgorithmParams(rank, numIterations, lambda, seed)``."""

    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: int = 3
    #: "auto" | "always" | "never" — use the Pallas streaming top-k for
    #: unconstrained queries (no categories/whiteList, whose filters need
    #: the dense mask) on huge catalogs, keeping the [B, I] score matrix
    #: out of HBM. Same selection rule as the recommendation template.
    streaming_top_k: str = "auto"


@dataclasses.dataclass
class SimilarALSModel:
    """Item-factor table + id maps (``ALSModel``,
    ``ALSAlgorithm.scala:25-53``); only ``productFeatures`` is needed for
    similarity scoring."""

    item_factors: np.ndarray  # [I, R]
    item_map: BiMap
    items: Dict[int, Item]

    def sanity_check(self) -> None:
        if not np.isfinite(self.item_factors).all():
            raise ValueError("SimilarALSModel factors are non-finite")

    @functools.cached_property
    def unit_factors(self) -> np.ndarray:
        """Row-normalized factors, computed once per model instance —
        cosine scoring needs them on every query, and renormalizing the
        whole table per request was the serving hot path's biggest host
        cost. Excluded from pickling (``__getstate__``) so persisted
        model blobs don't double in size; recomputed on first use after
        load."""
        norms = np.linalg.norm(self.item_factors, axis=1, keepdims=True)
        return self.item_factors / np.maximum(norms, 1e-12)

    @functools.cached_property
    def category_members(self) -> Dict[str, np.ndarray]:
        """category → member index arrays (see ``build_category_members``),
        built once per model instance; excluded from pickling like
        ``unit_factors``."""
        return build_category_members(self.items)

    def __getstate__(self):
        state = dict(self.__dict__)
        # cached_property stores under the property name
        state.pop("unit_factors", None)
        state.pop("category_members", None)
        return state


def build_category_members(items: Dict[int, Item]) -> Dict[str, np.ndarray]:
    """category → sorted int32 index array of member items.

    Turns the per-query category filter from an O(catalog) Python loop
    into a few vectorized index ops — the difference between microseconds
    and seconds per query on a large catalog. Shared by the
    similarproduct and ecommerce models (both cache it per instance)."""
    members: Dict[str, list] = {}
    for idx, item in items.items():
        for cat in item.categories:
            members.setdefault(cat, []).append(idx)
    return {
        c: np.asarray(sorted(ids), dtype=np.int32)
        for c, ids in members.items()
    }


def category_allowed_mask(
    members: Dict[str, np.ndarray], categories, n: int
) -> np.ndarray:
    """Bool mask of items belonging to ANY of ``categories`` (the
    ``isCandidateItem`` category rule); items absent from ``members``
    (never $set, or no categories) are not allowed — matching the old
    per-item ``items.get(i, Item())`` semantics."""
    allowed = np.zeros((n,), bool)
    for cat in categories:
        idx = members.get(cat)
        if idx is not None:
            allowed[idx] = True
    return allowed


def _candidate_mask(
    model: SimilarALSModel,
    query: Query,
    query_idx: Sequence[int],
) -> np.ndarray:
    """True = excluded. Mirrors ``isCandidateItem``: drop query items
    themselves, category-mismatched, non-whitelisted, blacklisted.
    Fully vectorized — no per-item Python loop (category membership comes
    from the model's precomputed index arrays)."""
    n = model.item_factors.shape[0]
    excluded = np.zeros((n,), bool)
    excluded[list(query_idx)] = True
    if query.categories is not None:
        excluded |= ~category_allowed_mask(
            model.category_members, query.categories, n
        )
    if query.white_list is not None:
        allowed = np.zeros((n,), bool)
        white_idx = [
            i for i in (model.item_map.get(it) for it in query.white_list)
            if i is not None
        ]
        allowed[white_idx] = True
        excluded |= ~allowed
    if query.black_list is not None:
        black_idx = [
            i for i in (model.item_map.get(it) for it in query.black_list)
            if i is not None
        ]
        excluded[black_idx] = True
    return excluded


class SimilarALSAlgorithm(Algorithm):
    """Implicit ALS over view counts; cosine-sum similarity predict
    (``ALSAlgorithm.scala:76-252``)."""

    params_class = SimilarALSParams

    def __init__(self, params: SimilarALSParams = SimilarALSParams()):
        self.params = params
        #: top-k path the LAST batch took ("streaming" | "dense"; None
        #: before the first query) — surfaced at /status.json like the
        #: recommendation template's
        self._topk_path = None

    @property
    def topk_path(self):
        return self._topk_path

    # -- train ------------------------------------------------------------
    def _ratings(self, pd: TrainingData) -> List[Tuple[str, str, float]]:
        """view count per (user, item) (``ALSAlgorithm.scala:98-119``)."""
        counts: Dict[Tuple[str, str], float] = {}
        for v in pd.view_events:
            counts[(v.user, v.item)] = counts.get((v.user, v.item), 0.0) + 1.0
        return [(u, i, c) for (u, i), c in counts.items()]

    def train(self, ctx, pd: TrainingData) -> SimilarALSModel:
        # a streaming_top_k typo must fail the training run, not the
        # first serving query after deploy (raises on unknown modes)
        use_streaming_topk(
            getattr(self.params, "streaming_top_k", "auto"), 1, 1
        )
        triplets = self._ratings(pd)
        if not triplets:
            raise ValueError(
                "similarproduct training events are empty; check DataSource"
            )
        user_map = BiMap.string_int(pd.users.keys())
        item_map = BiMap.string_int(pd.items.keys())
        valid = [
            (user_map.get(u), item_map.get(i), r)
            for u, i, r in triplets
            if user_map.get(u) is not None and item_map.get(i) is not None
        ]
        if not valid:
            # Training would silently produce an all-zero model (empty
            # solve): the usual cause is view events whose users/items
            # were never $set (the reference template only trains over
            # entities present in its users/items RDDs,
            # ``DataSource.scala`` of the similarproduct template).
            raise ValueError(
                f"No {type(self).__name__} rating events match $set "
                f"users/items: {len(triplets)} rating pairs, "
                f"{len(user_map)} users, {len(item_map)} items. Send $set "
                "events for the entities referenced by the interaction "
                "events."
            )
        users = np.array([v[0] for v in valid], np.int64)
        items = np.array([v[1] for v in valid], np.int64)
        vals = np.array([v[2] for v in valid], np.float32)
        factors = als_train_coo(
            users,
            items,
            vals,
            n_users=len(user_map),
            n_items=len(item_map),
            cfg=ALSConfig(
                rank=self.params.rank,
                iterations=self.params.num_iterations,
                lambda_=self.params.lambda_,
                implicit_prefs=True,
                alpha=1.0,
                seed=self.params.seed,
            ),
        )
        items_by_idx = {
            item_map[i]: item for i, item in pd.items.items()
        }
        return SimilarALSModel(
            item_factors=np.asarray(factors.item_factors),
            item_map=item_map,
            items=items_by_idx,
        )

    # -- predict ----------------------------------------------------------
    def predict(self, model: SimilarALSModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(
        self, model: SimilarALSModel, indexed_queries
    ) -> List[Tuple[int, PredictedResult]]:
        """Micro-batched serving path: ONE device dispatch for the whole
        batch via :func:`ops.scoring.top_k_for_vectors` (the [B, R] ×
        [R, I] cosine matmul + masked top-k on the MXU), with per-query
        candidate masks built on host — the batched analogue of the
        reference's per-request cosine scoring
        (``ALSAlgorithm.scala:76-252``). Shape bucketing (pad_pow2, as in
        the recommendation template) keeps the compiled-program set small
        across batch sizes."""
        import jax

        unit = model.unit_factors
        n_items = unit.shape[0]
        out: List[Tuple[int, PredictedResult]] = []
        rows = []  # (pos, query, query_idx)
        for pos, query in indexed_queries:
            query_idx = [
                model.item_map.get(it)
                for it in query.items
                if model.item_map.get(it) is not None
            ]
            if not query_idx:
                out.append((pos, PredictedResult(item_scores=())))
            else:
                rows.append((pos, query, query_idx))
        if not rows:
            return out
        # Σ_q cos(q, i) = (Σ_q unit_q) · unit_i
        qvecs = np.stack([unit[qi].sum(axis=0) for _, _, qi in rows])
        b = len(rows)
        b_pad = pad_pow2(b)
        max_k = min(max(q.num for _, q, _ in rows), n_items)
        k_pad = min(pad_pow2(max_k, lo=8), n_items)
        if b_pad > b:
            qvecs = np.pad(qvecs, ((0, b_pad - b), (0, 0)))
        self._topk_path = (
            "streaming"
            if self._use_streaming_topk(b_pad, n_items, rows)
            else "dense"
        )
        if self._topk_path == "streaming":
            # exclusions are small index lists (query items + blacklist):
            # the streaming kernel applies them per block without a dense
            # [B, I] mask, and the score matrix never touches HBM. The
            # dispatch rides the fused entry (one jitted program; its
            # resolve_topk_path decision matches this branch's
            # _use_streaming_topk for the unconstrained batches that
            # reach here — same (mode, b, n) inputs).
            from ..ops.scoring import top_k_fused_vectors

            excl_lists = []
            for _pos, q, qi in rows:
                black = [
                    i
                    for i in (
                        model.item_map.get(it) for it in (q.black_list or ())
                    )
                    if i is not None
                ]
                excl_lists.append(list(qi) + black)
            # bucket the exclusion width like b and k: a raw
            # data-dependent width would compile a fresh program per
            # distinct (query items + blacklist) length
            width = pad_pow2(max(len(l) for l in excl_lists), lo=16)
            excl = np.full((b_pad, width), -1, dtype=np.int32)
            for r, lst in enumerate(excl_lists):
                excl[r, : len(lst)] = lst
            scores, idx = top_k_fused_vectors(
                qvecs, unit, k_pad, excl,
                mode=getattr(self.params, "streaming_top_k", "auto"),
            )
        else:
            exclude = np.stack(
                [_candidate_mask(model, q, qi) for _, q, qi in rows]
            )
            if b_pad > b:
                # padded rows exclude everything → -inf scores, sliced away
                exclude = np.pad(
                    exclude, ((0, b_pad - b), (0, 0)), constant_values=True
                )
            scores, idx = top_k_for_vectors(qvecs, unit, k_pad, exclude)
        scores, idx = jax.device_get((scores, idx))
        scores = scores[:b, :max_k].tolist()
        idx = idx[:b, :max_k].tolist()
        inv = model.item_map.inverse
        for (pos, query, _qi), s_row, i_row in zip(rows, scores, idx):
            item_scores = []
            for s, i in zip(s_row[: query.num], i_row[: query.num]):
                # positive-cosine semantics: excluded (-inf) and
                # non-similar (<= 0) candidates never surface
                if s <= 0 or s != s:
                    continue
                item_scores.append(ItemScore(item=inv[int(i)], score=s))
            out.append((pos, PredictedResult(item_scores=tuple(item_scores))))
        return out

    def _use_streaming_topk(self, b_pad: int, n_items: int, rows) -> bool:
        """Streaming eligibility: category/whiteList filters need the
        dense mask (their exclusion sets are catalog-sized), so only
        unconstrained queries stream; size rule shared with the
        recommendation template (``ops.scoring.use_streaming_topk``)."""
        if any(
            q.categories is not None or q.white_list is not None
            for _, q, _ in rows
        ):
            # still validate the mode so a typo cannot hide behind a
            # constrained batch
            use_streaming_topk(
                getattr(self.params, "streaming_top_k", "auto"), 1, 1
            )
            return False
        return use_streaming_topk(
            getattr(self.params, "streaming_top_k", "auto"), b_pad, n_items
        )

    def query_class(self):
        return Query


class LikeAlgorithm(SimilarALSAlgorithm):
    """Same model over like/dislike signals: latest event per (user, item)
    wins; like→1, dislike→−1 (``LikeAlgorithm.scala:44-90``). Negative
    ratings act as high-confidence zero-preference in the implicit solver."""

    def _ratings(self, pd: TrainingData) -> List[Tuple[str, str, float]]:
        latest: Dict[Tuple[str, str], LikeEvent] = {}
        for e in pd.like_events:
            key = (e.user, e.item)
            if key not in latest or e.t > latest[key].t:
                latest[key] = e
        return [
            (e.user, e.item, 1.0 if e.like else -1.0) for e in latest.values()
        ]


class SimilarProductServing(Serving):
    """Z-score standardize per algorithm (unless ``num == 1``), sum by item,
    top-``num`` (``Serving.scala:14-53``)."""

    def serve(
        self, query: Query, predictions: Sequence[PredictedResult]
    ) -> PredictedResult:
        standardized: List[Tuple[str, float]] = []
        for pr in predictions:
            scores = np.array([s.score for s in pr.item_scores], np.float64)
            if query.num == 1 or scores.size == 0:
                z = scores
            else:
                std = scores.std()
                z = (
                    np.zeros_like(scores)
                    if std == 0
                    else (scores - scores.mean()) / std
                )
            standardized.extend(
                (s.item, float(zv)) for s, zv in zip(pr.item_scores, z)
            )
        combined: Dict[str, float] = {}
        for item, score in standardized:
            combined[item] = combined.get(item, 0.0) + score
        ranked = sorted(combined.items(), key=lambda kv: -kv[1])[: query.num]
        return PredictedResult(
            item_scores=tuple(ItemScore(item=i, score=s) for i, s in ranked)
        )


def engine_factory() -> Engine:
    """``SimilarProductEngine`` (multi ``Engine.scala``: ``Map("als" -> …,
    "likealgo" -> …)``)."""
    return Engine(
        {"": SimilarProductDataSource},
        {"": IdentityPreparator},
        {"als": SimilarALSAlgorithm, "likealgo": LikeAlgorithm},
        {"": SimilarProductServing},
    )
