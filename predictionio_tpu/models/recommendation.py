"""Recommendation engine template (ALS).

Rebuild of the reference's quickstart template
``examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/``:
``DataSource.scala:25-55`` reads "rate"/"buy" events from the event store,
``ALSAlgorithm.scala:27-70`` trains MLlib ALS over BiMap-translated indices,
``ALSAlgorithm.scala:72-86`` predicts via ``recommendProducts``. Here the
train step is the TPU ALS kernel (:mod:`predictionio_tpu.ops.als`) and
predict is the batched gather-dot top-k kernel
(:mod:`predictionio_tpu.ops.scoring`).
"""

from __future__ import annotations

import dataclasses
import logging
import weakref
from typing import List, Optional, Sequence, Tuple

import numpy as np

_logger = logging.getLogger(__name__)

from ..controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineParams,
    Evaluation,
    EngineParamsGenerator,
    FirstServing,
    OptionAverageMetric,
    Params,
    Preparator,
)
from ..ops.als import ALSConfig, als_train_coo
from ..ops.scoring import (
    pad_pow2,
    resolve_topk_path,
    top_k_for_users_fused,
    use_streaming_topk,
)
from ..storage import BiMap, get_registry
from ..workflow.infeed import stream_ratings


# -- queries / results (template's Query.scala / PredictedResult) -----------
@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...]

    def to_json_dict(self) -> dict:
        from .wire import item_scores_json

        return item_scores_json(self.item_scores)


# -- training data ----------------------------------------------------------
@dataclasses.dataclass
class TrainingData:
    """Streamed, pre-indexed ratings.

    The reference's TrainingData carries ``RDD[Rating]`` with *string* ids,
    translated later by the preparator (``DataSource.scala:25-55``). Here
    translation happens during the streaming read (12 bytes retained per
    rating instead of three Python strings), so TrainingData already holds
    dense indices plus the BiMaps to decode them — the host-memory contract
    of SURVEY §7 ("no triple materialization").
    """

    users: np.ndarray  # int32 [nnz]
    items: np.ndarray  # int32 [nnz]
    ratings: np.ndarray  # float32 [nnz]
    user_map: BiMap
    item_map: BiMap

    def sanity_check(self):
        if len(self.users) == 0:
            raise ValueError(
                "No rating events found; check app id and event names."
            )


@dataclasses.dataclass
class PreparedData:
    user_map: BiMap
    item_map: BiMap
    users: np.ndarray  # int32 [nnz]
    items: np.ndarray  # int32 [nnz]
    ratings: np.ndarray  # float32 [nnz]


# -- DASE components --------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class RecDataSourceParams(Params):
    app_id: int = 1
    event_names: Tuple[str, ...] = ("rate", "buy")
    buy_rating: float = 4.0  # implicit "buy" mapped to a rating, as in the
    # template's DataSource ("buy" treated as rate 4)


class RecDataSource(DataSource):
    """Reads rate/buy events via the columnar scan fast path
    (reference ``DataSource.scala:25-55`` via ``Storage.getPEvents().find``)."""

    params_class = RecDataSourceParams

    def __init__(self, params: RecDataSourceParams = RecDataSourceParams()):
        self.params = params

    def _value_rules(self) -> dict:
        """Per-event value rule (the template's rate/buy pattern-match):
        'rate' reads the required 'rating' property, 'buy' maps to a fixed
        implicit rating. Unsupported names fail in stream_ratings' rule
        lookup rather than pattern-match crash."""
        rules: dict = {}
        for name in self.params.event_names:
            if name == "rate":
                rules[name] = "rating"
            elif name == "buy":
                rules[name] = self.params.buy_rating
            else:
                raise ValueError(
                    f"Unsupported event {name!r} in recommendation "
                    "DataSource (supported: 'rate', 'buy')"
                )
        return rules

    def read_training(self, ctx) -> TrainingData:
        store = get_registry().get_events()
        batch = stream_ratings(
            store, self.params.app_id, self._value_rules()
        )
        return TrainingData(
            users=batch.users,
            items=batch.items,
            ratings=batch.ratings,
            user_map=batch.user_map,
            item_map=batch.item_map,
        )

    def read_eval(self, ctx):
        """K-fold by event index parity — mirrors the evaluation example's
        random splits but deterministic."""
        td = self.read_training(ctx)
        n = len(td.users)
        idx = np.arange(n)
        test = idx % 4 == 0
        u_inv, i_inv = td.user_map.inverse, td.item_map.inverse
        # Rebuild maps from the TRAIN split only: a user/item whose every
        # rating landed in the test split must be absent from the model's
        # maps so predict() takes the unknown-user path (empty result)
        # instead of scoring its never-solved zero factor row.
        tr_users, tr_items = td.users[~test], td.items[~test]
        uniq_u = np.unique(tr_users)
        uniq_i = np.unique(tr_items)
        u_remap = np.full(len(td.user_map), -1, dtype=np.int32)
        u_remap[uniq_u] = np.arange(len(uniq_u), dtype=np.int32)
        i_remap = np.full(len(td.item_map), -1, dtype=np.int32)
        i_remap[uniq_i] = np.arange(len(uniq_i), dtype=np.int32)
        train_td = TrainingData(
            users=u_remap[tr_users],
            items=i_remap[tr_items],
            ratings=td.ratings[~test],
            user_map=BiMap(
                {u_inv[int(old)]: new for new, old in enumerate(uniq_u)}
            ),
            item_map=BiMap(
                {i_inv[int(old)]: new for new, old in enumerate(uniq_i)}
            ),
        )
        qa = [
            (Query(user=u_inv[int(td.users[i])], num=10),
             ItemScore(item=i_inv[int(td.items[i])],
                       score=float(td.ratings[i])))
            for i in idx[test]
        ]
        return [(train_td, None, qa)]


class RecPreparator(Preparator):
    """Hands the streamed, pre-indexed ratings to the algorithm (reference
    custom-preparator variant, ``BiMap.stringInt`` usage — the string→index
    translation it performed now happens inside the streaming read, so
    preparation is a re-shape, not a copy)."""

    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(
            user_map=td.user_map,
            item_map=td.item_map,
            users=td.users,
            items=td.items,
            ratings=td.ratings,
        )


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    seed: int = 3
    implicit_prefs: bool = False
    alpha: float = 1.0
    #: Shard the training run over the workflow context's device mesh
    #: (solve rows on the ``data`` axis); "replicated" or "model" controls
    #: the factor-table layout (see :func:`ops.als.als_train`).
    distributed: bool = False
    factor_sharding: str = "replicated"
    #: Train with BOTH factor tables sharded over N devices via the
    #: ALX-style shard_map trainer (ops.als_sharded.als_train_sharded,
    #: docs/distributed_training.md). Tri-state per the PR-12 lever
    #: discipline: an explicit N wins, None resolves from
    #: ``PIO_TRAIN_SHARDS`` (what ``pio train --shards N`` sets), else 1 —
    #: the single-device trainer, byte-identical config resolution to
    #: today's path. Mutually exclusive with ``distributed`` (the
    #: pjit-annotation path) — conflicts fail loudly at train time,
    #: never silently pick one.
    shards: Optional[int] = None
    #: checkpoint factor tables every N iterations; a rerun of the same
    #: workflow resumes from the newest valid step. Tri-state
    #: (ckpt.resolve_every): explicit N (0 = explicitly off) wins, None
    #: resolves from the workflow run (``pio train --checkpoint-every``),
    #: else ``PIO_CKPT_EVERY``, else off. With ``shards > 1`` the
    #: sharded trainer snapshots canonical row order, so the resume
    #: shard count is free to differ (docs/checkpoint.md).
    checkpoint_every: Optional[int] = None
    #: "auto" | "chunked" | "two_phase" | "pallas" — see
    #: ops.als.ALSConfig.solve_mode ("auto" picks the fused pallas
    #: Cholesky kernel on a single-chip TPU run, "chunked" elsewhere)
    solve_mode: str = "auto"
    #: "f32" | "bf16" — gathered-factor precision for the normal-equation
    #: einsums (see ops.als.ALSConfig.gather_dtype; the bench's RMSE gate
    #: — docs/performance.md#levers — bounds the drift before adopting
    #: bf16)
    gather_dtype: str = "f32"
    #: Sort each solve row's column indices before staging (gather
    #: locality; permutation-invariant math). None (default) resolves to
    #: ON — pass False for the legacy unsorted path (see
    #: ops.als.ALSConfig.sort_gather_indices)
    sort_gather_indices: Optional[bool] = None
    #: Build normal equations with the fused gather+Gramian Pallas
    #: kernel. None (default) resolves to ON exactly when solve_mode
    #: resolves to "pallas" — pass False for the einsum build (see
    #: ops.als.ALSConfig.fused_gather)
    fused_gather: Optional[bool] = None
    #: Serving top-k path: "auto" (default) streams item blocks through
    #: the fused Pallas score+select kernel — never materializing the
    #: [batch, n_items] score matrix in HBM — when on TPU and that
    #: matrix would exceed 64 MB (ops.scoring.STREAMING_TOPK_BYTES);
    #: "always"/"never" force the choice. Serving dispatches through
    #: ops.scoring.top_k_for_users_fused (XLA lax.top_k fallback
    #: off-TPU) and /status.json reports the resolved path (topkPath).
    streaming_top_k: str = "auto"
    #: Serve top-k from an int8-quantized item table (per-row scales,
    #: docs/quantization.md) — ~4x less serving memory and item-table
    #: read traffic. Tri-state per the PR-12 lever discipline: explicit
    #: True/False wins, None resolves from ``PIO_SERVE_QUANT``
    #: ("1"/"0"), else OFF. Enabling runs the exactness gate at model
    #: attach (train / fold-in / first serve of a loaded model): the
    #: quantized top-k ids must match the f32 top-k on a probe set or
    #: the attach REFUSES loudly (quant.QuantGateError + counted
    #: metric) — never a silent quality slide. /status.json reports
    #: dtype, bytes, compression and the gate verdict (quantServing).
    quantized_serving: Optional[bool] = None
    #: Exactness-gate bound: minimum fraction of probe users whose
    #: quantized top-k id set must equal the f32 set. The default (1.0)
    #: demands identity; lowering it is an explicit operator decision
    #: (recorded in the gate status), the analogue of the bench's
    #: BENCH_BF16_RMSE_GATE override.
    quant_gate_min_match: float = 1.0


@dataclasses.dataclass
class ALSModel:
    """Factor tables + id maps (the ``MatrixFactorizationModel`` +
    ``IPersistentModel`` analogue, reference ``ALSModel.scala:1-63``).
    Plain numpy arrays so the workflow blob-persists it."""

    rank: int
    user_factors: np.ndarray  # [U, rank] float32
    item_factors: np.ndarray  # [I, rank] float32
    user_map: BiMap
    item_map: BiMap

    def sanity_check(self):
        if not np.isfinite(self.user_factors).all():
            raise ValueError("ALS produced non-finite user factors")
        if not np.isfinite(self.item_factors).all():
            raise ValueError("ALS produced non-finite item factors")


class ALSAlgorithm(Algorithm):
    """TPU ALS (reference ``ALSAlgorithm.scala:27-86``)."""

    params_class = ALSAlgorithmParams

    def __init__(self, params: ALSAlgorithmParams = ALSAlgorithmParams()):
        self.params = params
        #: the top-k path the LAST batch actually took ("streaming" |
        #: "dense" | "quant"; None before the first query) — the
        #: resolved serving lever, read by the query server's
        #: /status.json
        self._topk_path: Optional[str] = None
        # quantized-serving state: the gated table for the attached
        # model (weakref identity — a fold-in's new model re-gates) and
        # the gate status /status.json surfaces
        self._quant = None
        self._quant_model_ref = None
        self._quant_status: Optional[dict] = None

    @property
    def topk_path(self) -> Optional[str]:
        return self._topk_path

    @property
    def quant_status(self) -> Optional[dict]:
        """The quantized-serving gate status for the attached model
        (dtype, bytes, compression, matchRate) — None while the lever
        is off. Read by /status.json (quantServing)."""
        return self._quant_status

    def _attach_quant(self, model: ALSModel) -> None:
        """Resolve the quantized_serving lever against THIS model.

        Runs the exactness gate once per attached model — at train and
        fold-in return, and on the first serve of a model loaded from
        the blob store — always BEFORE any quantized answer is
        produced. A gate refusal propagates (loud + counted, the
        docs/quantization.md#gate contract); it never falls back to
        f32 silently."""
        from ..quant import quantize_serving_table, resolve_quantized_serving

        if not resolve_quantized_serving(self.params.quantized_serving):
            self._quant = None
            self._quant_model_ref = None
            self._quant_status = None
            return
        if (
            self._quant is not None
            and self._quant_model_ref is not None
            and self._quant_model_ref() is model
        ):
            return
        qtable, status = quantize_serving_table(
            model.item_factors,
            model.user_factors,
            min_match=self.params.quant_gate_min_match,
        )
        status["minMatch"] = self.params.quant_gate_min_match
        self._quant = qtable
        self._quant_model_ref = weakref.ref(model)
        self._quant_status = status

    def train(self, ctx, pd: PreparedData) -> ALSModel:
        p = self.params
        # a config typo must fail the training run, not the first serving
        # query after deploy (use_streaming_topk raises on unknown modes)
        use_streaming_topk(p.streaming_top_k, 1, 1)
        cfg = ALSConfig(
            rank=p.rank,
            iterations=p.num_iterations,
            lambda_=p.lambda_,
            seed=p.seed,
            implicit_prefs=p.implicit_prefs,
            alpha=p.alpha,
            solve_mode=p.solve_mode,
            gather_dtype=p.gather_dtype,
            sort_gather_indices=p.sort_gather_indices,
            fused_gather=p.fused_gather,
        )
        from ..ckpt import resolve_every, resolve_resume
        from ..ops.als_sharded import als_train_sharded, resolve_shards

        shards = resolve_shards(p.shards)
        # checkpoint cadence: params > workflow run (--checkpoint-every /
        # the continuous retrain config) > PIO_CKPT_EVERY > off; an
        # invalid value refuses here, at train time
        every = resolve_every(
            p.checkpoint_every,
            workflow=getattr(ctx, "checkpoint_every", None),
        )
        if shards > 1:
            # the ALX-style sharded data plane (docs/distributed_training
            # .md): both factor tables sharded over the mesh data axis.
            # Conflicting levers fail loudly — a silently ignored flag
            # would corrupt the hardware A/B (the PR-12 discipline).
            if p.distributed:
                raise ValueError(
                    "shards > 1 and distributed=True are mutually "
                    "exclusive: the sharded trainer builds its own mesh "
                    "(pass one or the other)"
                )
            store = None
            if every > 0 and ctx is not None:
                store_factory = getattr(ctx, "checkpoint_store", None)
                if store_factory:
                    # one namespace per algorithm slot, disjoint from the
                    # single-device manager's: the canonical-row store
                    # and the pytree manager must never read each other
                    store = store_factory(
                        subdir="algo_"
                        f"{getattr(ctx, 'algorithm_index', 0)}_sharded"
                    )
                if store is not None and not resolve_resume():
                    store.clear()  # --no-resume: train fresh
            factors = als_train_sharded(
                pd.users,
                pd.items,
                pd.ratings,
                n_users=len(pd.user_map),
                n_items=len(pd.item_map),
                cfg=cfg,
                shards=shards,
                checkpoint=store,
                checkpoint_every=every if store is not None else 0,
            )
            model = ALSModel(
                rank=p.rank,
                user_factors=np.asarray(factors.user_factors),
                item_factors=np.asarray(factors.item_factors),
                user_map=pd.user_map,
                item_map=pd.item_map,
            )
            self._attach_quant(model)
            return model
        mesh = ctx.mesh if (p.distributed and ctx is not None) else None
        checkpoint = None
        if every > 0 and ctx is not None:
            manager_factory = getattr(ctx, "checkpoint_manager", None)
            if manager_factory:
                # one namespace per algorithm slot: a second ALS block in the
                # same engine must never resume from this one's factors
                checkpoint = manager_factory(
                    subdir=f"algo_{getattr(ctx, 'algorithm_index', 0)}"
                )
                if checkpoint is not None and not resolve_resume():
                    import os
                    import shutil

                    # --no-resume: train fresh (the manager recreates
                    # the empty dir it expects to list)
                    shutil.rmtree(checkpoint.directory, ignore_errors=True)
                    os.makedirs(checkpoint.directory, exist_ok=True)
        factors = als_train_coo(
            pd.users,
            pd.items,
            pd.ratings,
            n_users=len(pd.user_map),
            n_items=len(pd.item_map),
            cfg=cfg,
            mesh=mesh,
            factor_sharding=p.factor_sharding,
            checkpoint=checkpoint,
            checkpoint_every=every,
        )
        model = ALSModel(
            rank=p.rank,
            user_factors=np.asarray(factors.user_factors),
            item_factors=np.asarray(factors.item_factors),
            user_map=pd.user_map,
            item_map=pd.item_map,
        )
        # quantized-serving gate at train time (a refusal must surface
        # here, not on the first query after deploy — the same reasoning
        # as the use_streaming_topk validation above)
        self._attach_quant(model)
        return model

    @property
    def fold_in_supported(self) -> bool:
        """Fold-in solves the EXPLICIT normal equations; an
        implicit-prefs model (Hu-Koren confidence weighting,
        ``_system_implicit``) would get mathematically wrong row updates
        — the continuous controller escalates those engines to a full
        retrain instead (docs/continuous.md)."""
        return not self.params.implicit_prefs

    def _fold_base(self, model: ALSModel, pd: PreparedData) -> dict:
        """The fold prologue shared by :meth:`fold_in` and
        :meth:`fold_in_partitioned`: extend the model's id maps with
        pd's universe (stable indices), translate pd's COO into the
        combined space, and seed rows for new entities. Deterministic in
        (model, pd) — every concurrent partition fold starts from this
        SAME extended base, which is what makes their results
        mergeable."""
        from ..continuous.foldin import extend_bimap_indexing, seeded_rows

        p = self.params
        rank = model.user_factors.shape[1]
        old_u, old_i = len(model.user_map), len(model.item_map)
        # pd's maps are freshly built in arrival order — append the ids
        # the baseline has never seen, preserving every existing index
        pd_u_ids = [pd.user_map.inverse[i] for i in range(len(pd.user_map))]
        pd_i_ids = [pd.item_map.inverse[i] for i in range(len(pd.item_map))]
        comb_u, new_u = extend_bimap_indexing(model.user_map.to_dict(), pd_u_ids)
        comb_i, new_i = extend_bimap_indexing(model.item_map.to_dict(), pd_i_ids)
        # translate pd's index space into the combined space via id strings
        t_u = np.asarray([comb_u[k] for k in pd_u_ids], dtype=np.int32)
        t_i = np.asarray([comb_i[k] for k in pd_i_ids], dtype=np.int32)
        uf = np.concatenate(
            [
                np.asarray(model.user_factors, dtype=np.float32),
                seeded_rows(new_u, rank, p.seed, offset=old_u),
            ]
        )
        itf = np.concatenate(
            [
                np.asarray(model.item_factors, dtype=np.float32),
                seeded_rows(new_i, rank, p.seed + 1, offset=old_i),
            ]
        )
        return {
            "rank": rank,
            "old_u": old_u,
            "old_i": old_i,
            "new_u": new_u,
            "new_i": new_i,
            "comb_u": comb_u,
            "comb_i": comb_i,
            "users": t_u[pd.users],
            "items": t_i[pd.items],
            "uf": uf,
            "itf": itf,
        }

    def fold_in(
        self,
        ctx,
        model: ALSModel,
        pd: PreparedData,
        changed_user_ids: Sequence[str],
        changed_item_ids: Sequence[str],
        policy=None,
    ):
        """ALX-style incremental update (``docs/continuous.md``): re-solve
        only the changed/new user and item rows against fixed counterpart
        factors, over the full current data ``pd``. Existing entities keep
        their indices (untouched rows stay byte-identical); new entities
        get appended, seeded rows. Returns ``(ALSModel, FoldInStats)``.
        """
        from ..continuous.foldin import (
            FoldInPolicy,
            FoldInStats,
            fold_in_factors,
        )
        from ..ops.als import ALSFactors, rmse

        if not self.fold_in_supported:
            raise ValueError(
                "fold_in solves explicit normal equations; "
                "implicit_prefs=True models must retrain fully"
            )
        policy = policy or FoldInPolicy()
        base = self._fold_base(model, pd)
        rank, users, items = base["rank"], base["users"], base["items"]
        uf, itf = base["uf"], base["itf"]
        comb_u, comb_i = base["comb_u"], base["comb_i"]
        changed_u = sorted(
            {comb_u[k] for k in changed_user_ids if k in comb_u}
            | set(range(base["old_u"], base["old_u"] + base["new_u"]))
        )
        changed_i = sorted(
            {comb_i[k] for k in changed_item_ids if k in comb_i}
            | set(range(base["old_i"], base["old_i"] + base["new_i"]))
        )
        before = rmse(ALSFactors(uf, itf, rank), users, items, pd.ratings)
        uf, itf, counts = fold_in_factors(
            uf, itf, users, items, pd.ratings,
            changed_u, changed_i, self.params.lambda_, policy=policy,
        )
        after = rmse(ALSFactors(uf, itf, rank), users, items, pd.ratings)
        folded = ALSModel(
            rank=model.rank,
            user_factors=uf,
            item_factors=itf,
            user_map=BiMap(comb_u),
            item_map=BiMap(comb_i),
        )
        stats = FoldInStats(
            folded_users=counts["solved_users"],
            folded_items=counts["solved_items"],
            new_users=base["new_u"],
            new_items=base["new_i"],
            rmse_before=before,
            rmse_after=after,
        )
        # re-gate the folded table: fold-in moved item rows, so the old
        # quantized table (if any) is stale and the new one must prove
        # exactness again before it serves
        self._attach_quant(folded)
        return folded, stats

    def fold_in_partitioned(
        self,
        ctx,
        model: ALSModel,
        pd: PreparedData,
        parts,
        policy=None,
        max_workers: int = 2,
        timeout_s: float = 0.0,
        clock=None,
    ):
        """Fold per-partition deltas CONCURRENTLY on a bounded pool
        (docs/continuous.md#partitioned-folds).

        ``parts`` maps partition index → ``(user_ids, item_ids)`` — the
        per-keyspace deltas ``PartitionedFeedWatcher.take_batches``
        yields. Every partition's fold runs :func:`fold_in_factors` over
        the SAME extended base tables (so results merge by row copy):
        the write-path hash partitions users, making the per-partition
        changed-user row sets disjoint; changed-item rows may overlap and
        merge last-partition-wins — both solves read the full rating
        corpus against the same base, so the difference is bounded by the
        user-row deltas and the RMSE drift gate guards the composition.

        ``timeout_s > 0`` bounds the wait: a partition whose fold has not
        finished by the deadline (or raised) is SKIPPED — excluded from
        the merge and from the returned ``completed`` list, so the
        controller never commits its cursor and its delta re-folds next
        cycle (convergent, the watcher's replay contract). A slow
        partition therefore never blocks another partition's commit.
        ``timeout_s == 0`` waits for every partition.

        Returns ``(ALSModel, FoldInStats, completed)`` — stats measured
        on the MERGED model. Raises ``RuntimeError`` when no partition
        completed (nothing to commit)."""
        import concurrent.futures
        import time as _time

        from ..continuous.foldin import (
            FoldInPolicy,
            FoldInStats,
            fold_in_factors,
        )
        from ..ops.als import ALSFactors, rmse

        if not self.fold_in_supported:
            raise ValueError(
                "fold_in solves explicit normal equations; "
                "implicit_prefs=True models must retrain fully"
            )
        policy = policy or FoldInPolicy()
        clock = clock or _time.monotonic
        base = self._fold_base(model, pd)
        rank, users, items = base["rank"], base["users"], base["items"]
        comb_u, comb_i = base["comb_u"], base["comb_i"]
        new_u_rows = set(range(base["old_u"], base["old_u"] + base["new_u"]))
        new_i_rows = set(range(base["old_i"], base["old_i"] + base["new_i"]))
        changed: dict = {}
        claimed_u: set = set()
        claimed_i: set = set()
        for idx in sorted(parts):
            user_ids, item_ids = parts[idx]
            cu = {comb_u[k] for k in user_ids if k in comb_u}
            ci = {comb_i[k] for k in item_ids if k in comb_i}
            changed[idx] = (cu, ci)
            claimed_u |= cu
            claimed_i |= ci
        # new entities nobody's delta named (races between the batch
        # snapshot and the pd read) go to EVERY partition: identical
        # inputs solve to identical rows, so whichever folds complete
        # cover them and the merge copies are byte-equal
        orphan_u = new_u_rows - claimed_u
        orphan_i = new_i_rows - claimed_i
        for idx, (cu, ci) in changed.items():
            cu |= orphan_u
            ci |= orphan_i

        before = rmse(
            ALSFactors(base["uf"], base["itf"], rank),
            users, items, pd.ratings,
        )
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, min(len(changed), int(max_workers))),
            thread_name_prefix="fold-part",
        )
        futures = {
            idx: pool.submit(
                fold_in_factors,
                base["uf"], base["itf"], users, items, pd.ratings,
                sorted(cu), sorted(ci), self.params.lambda_, policy=policy,
            )
            for idx, (cu, ci) in sorted(changed.items())
        }
        deadline = clock() + timeout_s if timeout_s > 0 else None
        concurrent.futures.wait(
            futures.values(),
            timeout=None if deadline is None else max(0.0, deadline - clock()),
        )
        # a wedged fold thread keeps running past the deadline (threads
        # cannot be killed) but is bounded by the pool size and holds
        # only the shared read-only base arrays; never join on it —
        # queued-but-unstarted folds ARE cancellable and must not burn
        # the next cycle's CPU on thrown-away results
        pool.shutdown(wait=False, cancel_futures=True)
        uf = np.array(base["uf"], dtype=np.float32, copy=True)
        itf = np.array(base["itf"], dtype=np.float32, copy=True)
        completed = []
        folded_users = folded_items = 0
        for idx in sorted(futures):
            fut = futures[idx]
            if not fut.done() or fut.cancelled():
                # timed out (or cancelled while queued): cursor stays
                # put, delta re-folds next cycle
                _logger.warning(
                    "fold_in_partitioned: partition %d missed the "
                    "%.1fs deadline; skipped (delta re-folds)",
                    idx, timeout_s,
                )
                continue
            if fut.exception() is not None:
                # a failing partition must be DIAGNOSABLE, not a bare
                # skip counter: the error is logged here, the cursor
                # stays put, and the delta re-folds (a deterministic
                # failure keeps logging every cycle — loud by design)
                _logger.warning(
                    "fold_in_partitioned: partition %d fold failed; "
                    "skipped (delta re-folds)",
                    idx, exc_info=fut.exception(),
                )
                continue
            uf_p, itf_p, counts = fut.result()
            cu, ci = changed[idx]
            cu_rows = np.asarray(sorted(cu), dtype=np.int64)
            ci_rows = np.asarray(sorted(ci), dtype=np.int64)
            if len(cu_rows):
                uf[cu_rows] = uf_p[cu_rows]
            if len(ci_rows):
                itf[ci_rows] = itf_p[ci_rows]
            completed.append(idx)
            folded_users += counts["solved_users"]
            folded_items += counts["solved_items"]
        if not completed:
            raise RuntimeError(
                f"no partition fold completed within {timeout_s}s "
                f"(partitions {sorted(futures)}) — nothing to commit"
            )
        after = rmse(ALSFactors(uf, itf, rank), users, items, pd.ratings)
        folded = ALSModel(
            rank=model.rank,
            user_factors=uf,
            item_factors=itf,
            user_map=BiMap(comb_u),
            item_map=BiMap(comb_i),
        )
        stats = FoldInStats(
            folded_users=folded_users,
            folded_items=folded_items,
            new_users=base["new_u"],
            new_items=base["new_i"],
            rmse_before=before,
            rmse_after=after,
        )
        self._attach_quant(folded)  # merged table re-gates (see fold_in)
        return folded, stats, completed

    def shard_model(
        self, model: ALSModel, shard_index: int, shard_count: int
    ) -> ALSModel:
        """One item-factor partition for sharded serving
        (``docs/fleet.md``; the serving-side analogue of ALX's sharded
        factor layout). Item row ``i`` lives on shard ``i % shard_count``
        — round-robin, so power-law-popular head items spread across
        shards instead of piling onto shard 0. User factors stay whole
        (queries score a full user row against the local partition), the
        item map is rebuilt over the kept rows, and the union of all
        shards' local top-ks provably contains the global top-k the
        router merge reconstructs exactly."""
        keep = np.arange(
            shard_index, model.item_factors.shape[0], shard_count
        )
        inv = model.item_map.inverse
        return ALSModel(
            rank=model.rank,
            user_factors=model.user_factors,
            item_factors=np.ascontiguousarray(model.item_factors[keep]),
            user_map=model.user_map,
            item_map=BiMap(
                {inv[int(old)]: new for new, old in enumerate(keep)}
            ),
        )

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        results = self.batch_predict(model, [(0, query)])
        return results[0][1]

    def batch_predict(
        self, model: ALSModel, indexed_queries: Sequence[Tuple[int, Query]]
    ) -> List[Tuple[int, PredictedResult]]:
        """One device call for the whole batch (reference batchPredict is a
        per-query cartesian; here it's a single gather-dot top-k)."""
        known = [
            (i, q) for i, q in indexed_queries if model.user_map.get(q.user) is not None
        ]
        out: List[Tuple[int, PredictedResult]] = [
            (i, PredictedResult(item_scores=()))
            for i, q in indexed_queries
            if model.user_map.get(q.user) is None
        ]
        if known:
            n_items = model.item_factors.shape[0]
            max_k = min(max(q.num for _, q in known), n_items)
            user_idx = np.asarray(
                [model.user_map[q.user] for _, q in known], dtype=np.int32
            )
            # Shape bucketing (ops/scoring.pad_pow2): micro-batched serving
            # produces every batch size — pad B and k to powers of two so
            # the device program set stays O(log^2), then slice on host.
            b = len(user_idx)
            b_pad = pad_pow2(b)
            k_pad = min(pad_pow2(max_k, lo=8), n_items)
            padded_idx = np.pad(user_idx, (0, b_pad - b))
            # gate-or-refuse BEFORE any answer when the quantized lever
            # is on and this model (e.g. loaded from the blob store)
            # has not been gated yet — a query must never be served
            # from ungated codes
            self._attach_quant(model)
            if self._quant is not None:
                # quantized serving: scores from int8 codes + per-row
                # scales (quant.top_k_quantized) — licensed by the
                # exactness gate _attach_quant just ran/cached
                from ..quant import top_k_quantized

                self._topk_path = "quant"
                scores, items = top_k_quantized(
                    model.user_factors, self._quant, padded_idx, k=k_pad
                )
            else:
                # the fused score+select entry dispatches: Pallas
                # streaming on TPU past the use_streaming_topk bar (the
                # [B, I] score matrix never exists), XLA score +
                # lax.top_k below it — record which path serves
                # (resolve_topk_path is the ONE decision home the entry
                # itself dispatches on, same (mode, b, n) inputs),
                # surfaced at /status.json
                self._topk_path = resolve_topk_path(
                    self.params.streaming_top_k, b_pad, n_items
                )
                scores, items = top_k_for_users_fused(
                    model.user_factors, model.item_factors, padded_idx,
                    k=k_pad, mode=self.params.streaming_top_k,
                )
            # one fetch for both arrays: each device_get is a full host↔
            # device round trip, which dominates per-batch latency on
            # high-latency links (tunneled/remote devices)
            import jax

            scores, items = jax.device_get((scores, items))
            # bulk ndarray→python conversion: one C call instead of
            # 2×B×k scalar __float__/__int__ calls on the hot path
            scores = scores[:b, :max_k].tolist()
            items = items[:b, :max_k].tolist()
            inv = model.item_map.inverse
            for row, (i, q) in enumerate(known):
                k = min(q.num, max_k)
                s_row, i_row = scores[row], items[row]
                out.append(
                    (
                        i,
                        PredictedResult(
                            item_scores=tuple(
                                ItemScore(item=inv[i_row[j]], score=s_row[j])
                                for j in range(k)
                            )
                        ),
                    )
                )
        return out

    def query_class(self):
        return Query


def engine_factory() -> Engine:
    """The template's EngineFactory (reference ``Engine.scala`` of the
    template: ``RecommendationEngine``)."""
    return Engine(
        {"": RecDataSource},
        {"": RecPreparator},
        {"als": ALSAlgorithm, "": ALSAlgorithm},
        {"": FirstServing},
    )


# -- evaluation (reference evaluation example: Precision@K on MovieLens,
#    examples/experimental/scala-local-movielens-evaluation/src/main/scala/
#    Evaluation.scala:83,115) --------------------------------------------
class PrecisionAtK(OptionAverageMetric):
    """Fraction of relevant held-out interactions recovered in the top-k.

    A held-out (query, actual) row counts only when the actual rating meets
    ``rating_threshold`` (irrelevant rows are skipped — the Option part);
    the point score is 1.0 when the actual item appears in the predicted
    top-k."""

    def __init__(self, k: int = 10, rating_threshold: float = 4.0):
        self.k = k
        self.rating_threshold = rating_threshold

    @property
    def header(self) -> str:
        return f"Precision@{self.k} (threshold={self.rating_threshold})"

    def calculate_point(self, q, p, a) -> Optional[float]:
        if a.score < self.rating_threshold:
            return None
        top = [s.item for s in p.item_scores[: self.k]]
        return 1.0 if a.item in top else 0.0


class RecEvaluation(Evaluation):
    """``pio eval`` target for this template."""

    def __init__(self, k: int = 10, rating_threshold: float = 4.0):
        super().__init__()
        self.engine_metric = (
            engine_factory(),
            PrecisionAtK(k=k, rating_threshold=rating_threshold),
        )


class RecParamsGenerator(EngineParamsGenerator):
    """Hyperparameter grid over rank x lambda (the reference example's
    EngineParamsGenerator pattern)."""

    def __init__(
        self,
        app_id: int = 1,
        ranks: Sequence[int] = (8, 16),
        lambdas: Sequence[float] = (0.01, 0.1),
    ):
        base_ds = RecDataSourceParams(app_id=app_id)
        grid = [
            EngineParams(
                data_source_params=("", base_ds),
                algorithm_params_list=[
                    ("als", ALSAlgorithmParams(rank=r, lambda_=lam)),
                ],
            )
            for r in ranks
            for lam in lambdas
        ]
        super().__init__(grid)
