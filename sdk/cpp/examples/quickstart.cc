// SDK quickstart: ingest a few events, query a deployed engine.
//
// Build:  g++ -std=c++17 -O2 -I.. quickstart.cc ../predictionio_client.cc \
//             -o quickstart
// Run:    ./quickstart <event_host> <event_port> <access_key> \
//                      [<engine_host> <engine_port>]
//
// Mirrors the reference Java SDK quickstart shape: EventClient for
// ingestion, EngineClient for queries.

#include <cstdio>
#include <cstdlib>

#include "predictionio_client.hpp"

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr,
            "usage: %s <event_host> <event_port> <access_key> "
            "[<engine_host> <engine_port>]\n",
            argv[0]);
    return 2;
  }
  try {
    pio::EventClient events(argv[1], atoi(argv[2]), argv[3]);
    std::string id = events.create_event(
        R"({"event": "rate", "entityType": "user", "entityId": "u1",)"
        R"( "targetEntityType": "item", "targetEntityId": "i1",)"
        R"( "properties": {"rating": 5.0}})");
    printf("created event: %s\n", id.c_str());
    std::string fetched = events.get_event(id);
    printf("fetched: %s\n", fetched.c_str());

    if (argc >= 6) {
      pio::EngineClient engine(argv[4], atoi(argv[5]));
      std::string result =
          engine.send_query(R"({"user": "u1", "num": 4})");
      printf("query result: %s\n", result.c_str());
    }
    return 0;
  } catch (const pio::ClientError& e) {
    fprintf(stderr, "client error (HTTP %d): %s\n", e.status(), e.what());
    return 1;
  }
}
