// Engine-authoring helper for C++ DASE components.
//
// The counterpart of the reference's Java authoring shim
// (core/src/main/scala/io/prediction/controller/java/LJavaAlgorithm.scala
// and siblings): where the reference lets JVM languages implement DASE
// roles in-process, this framework runs a foreign component as a child
// process speaking line-delimited JSON on stdin/stdout (see
// predictionio_tpu/controller/foreign.py for the protocol). This header
// provides everything a C++ component needs: a small self-contained JSON
// value type (parse + serialize) and pio::engine_main(), the stdio
// request loop.
//
// Usage (see examples/cpp_engine/popularity.cc):
//
//   #include "pio_engine.hpp"
//   int main() {
//     pio::Handlers h;
//     h.train   = [](const pio::Json& params, const pio::Json& data) { ... };
//     h.predict = [](const pio::Json& model, const pio::Json& query) { ... };
//     return pio::engine_main(h);
//   }
//
// Handlers throw std::runtime_error to report a component-level failure;
// engine_main turns it into an {"error": ...} response and keeps serving
// (one bad query must not kill the process — micro-batch parity with the
// in-tree serving path).

#ifndef PIO_ENGINE_HPP_
#define PIO_ENGINE_HPP_

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <iostream>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace pio {

// ---------------------------------------------------------------------------
// Json: a compact tagged-union JSON value (enough for the wire protocol).
// ---------------------------------------------------------------------------

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(double d) : type_(Type::Number), num_(d) {}
  Json(int i) : type_(Type::Number), num_(i) {}
  Json(int64_t i) : type_(Type::Number), num_((double)i) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}

  static Json array() { Json j; j.type_ = Type::Array; return j; }
  static Json object() { Json j; j.type_ = Type::Object; return j; }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool as_bool() const { expect(Type::Bool); return bool_; }
  double as_number() const { expect(Type::Number); return num_; }
  int64_t as_int() const { expect(Type::Number); return (int64_t)num_; }
  const std::string& as_string() const { expect(Type::String); return str_; }
  const std::vector<Json>& items() const { expect(Type::Array); return arr_; }
  const std::map<std::string, Json>& fields() const {
    expect(Type::Object);
    return obj_;
  }

  // object access; missing key -> Null
  const Json& operator[](const std::string& key) const {
    static const Json kNull;
    if (type_ != Type::Object) return kNull;
    auto it = obj_.find(key);
    return it == obj_.end() ? kNull : it->second;
  }
  void set(const std::string& key, Json v) {
    expect(Type::Object);
    obj_[key] = std::move(v);
  }
  void push(Json v) { expect(Type::Array); arr_.push_back(std::move(v)); }
  size_t size() const {
    return type_ == Type::Array ? arr_.size()
         : type_ == Type::Object ? obj_.size() : 0;
  }

  // -- serialize ------------------------------------------------------------
  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

  // -- parse ----------------------------------------------------------------
  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size())
      throw std::runtime_error("JSON: trailing characters");
    return v;
  }

 private:
  void expect(Type t) const {
    if (type_ != t) throw std::runtime_error("JSON: wrong type access");
  }

  void write(std::ostringstream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == (double)(int64_t)num_ &&
            std::abs(num_) < 1e15) {
          os << (int64_t)num_;
        } else {
          char buf[32];
          snprintf(buf, sizeof(buf), "%.17g", num_);
          os << buf;
        }
        break;
      }
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[';
        for (size_t i = 0; i < arr_.size(); i++) {
          if (i) os << ',';
          arr_[i].write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& kv : obj_) {
          if (!first) os << ',';
          first = false;
          write_string(os, kv.first);
          os << ':';
          kv.second.write(os);
        }
        os << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;  // UTF-8 bytes pass through
          }
      }
    }
    os << '"';
  }

  static void skip_ws(const std::string& t, size_t& p) {
    while (p < t.size() &&
           (t[p] == ' ' || t[p] == '\t' || t[p] == '\n' || t[p] == '\r'))
      p++;
  }

  static Json parse_value(const std::string& t, size_t& p) {
    skip_ws(t, p);
    if (p >= t.size()) throw std::runtime_error("JSON: unexpected end");
    char c = t[p];
    if (c == '{') return parse_object(t, p);
    if (c == '[') return parse_array(t, p);
    if (c == '"') return Json(parse_string(t, p));
    if (c == 't') { expect_lit(t, p, "true"); return Json(true); }
    if (c == 'f') { expect_lit(t, p, "false"); return Json(false); }
    if (c == 'n') { expect_lit(t, p, "null"); return Json(); }
    return parse_number(t, p);
  }

  static void expect_lit(const std::string& t, size_t& p, const char* lit) {
    size_t n = strlen(lit);
    if (t.compare(p, n, lit) != 0)
      throw std::runtime_error("JSON: bad literal");
    p += n;
  }

  static Json parse_number(const std::string& t, size_t& p) {
    size_t start = p;
    if (p < t.size() && (t[p] == '-' || t[p] == '+')) p++;
    while (p < t.size() &&
           (isdigit((unsigned char)t[p]) || t[p] == '.' || t[p] == 'e' ||
            t[p] == 'E' || t[p] == '-' || t[p] == '+'))
      p++;
    try {
      return Json(std::stod(t.substr(start, p - start)));
    } catch (...) {
      throw std::runtime_error("JSON: bad number");
    }
  }

  static std::string parse_string(const std::string& t, size_t& p) {
    if (t[p] != '"') throw std::runtime_error("JSON: expected string");
    p++;
    std::string out;
    while (p < t.size() && t[p] != '"') {
      char c = t[p];
      if (c == '\\') {
        p++;
        if (p >= t.size()) throw std::runtime_error("JSON: bad escape");
        char e = t[p];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (p + 4 >= t.size())
              throw std::runtime_error("JSON: bad \\u escape");
            unsigned cp = (unsigned)strtoul(t.substr(p + 1, 4).c_str(),
                                            nullptr, 16);
            p += 4;
            // Surrogate pair: \uD800-\uDBFF must be followed by
            // \uDC00-\uDFFF — combine into one code point (Python's
            // json.dumps(ensure_ascii=True) sends every emoji this way).
            // A lone/mismatched surrogate folds to U+FFFD.
            if (cp >= 0xD800 && cp <= 0xDBFF) {
              if (p + 6 < t.size() && t[p + 1] == '\\' && t[p + 2] == 'u') {
                unsigned lo = (unsigned)strtoul(
                    t.substr(p + 3, 4).c_str(), nullptr, 16);
                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                  p += 6;
                } else {
                  cp = 0xFFFD;
                }
              } else {
                cp = 0xFFFD;
              }
            } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
              cp = 0xFFFD;  // lone low surrogate
            }
            if (cp < 0x80) {
              out += (char)cp;
            } else if (cp < 0x800) {
              out += (char)(0xC0 | (cp >> 6));
              out += (char)(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
              out += (char)(0xE0 | (cp >> 12));
              out += (char)(0x80 | ((cp >> 6) & 0x3F));
              out += (char)(0x80 | (cp & 0x3F));
            } else {
              out += (char)(0xF0 | (cp >> 18));
              out += (char)(0x80 | ((cp >> 12) & 0x3F));
              out += (char)(0x80 | ((cp >> 6) & 0x3F));
              out += (char)(0x80 | (cp & 0x3F));
            }
            break;
          }
          default: throw std::runtime_error("JSON: bad escape");
        }
        p++;
      } else {
        out += c;
        p++;
      }
    }
    if (p >= t.size()) throw std::runtime_error("JSON: unterminated string");
    p++;  // closing quote
    return out;
  }

  static Json parse_array(const std::string& t, size_t& p) {
    Json a = Json::array();
    p++;  // [
    skip_ws(t, p);
    if (p < t.size() && t[p] == ']') { p++; return a; }
    while (true) {
      a.push(parse_value(t, p));
      skip_ws(t, p);
      if (p >= t.size()) throw std::runtime_error("JSON: unterminated array");
      if (t[p] == ',') { p++; continue; }
      if (t[p] == ']') { p++; return a; }
      throw std::runtime_error("JSON: bad array separator");
    }
  }

  static Json parse_object(const std::string& t, size_t& p) {
    Json o = Json::object();
    p++;  // {
    skip_ws(t, p);
    if (p < t.size() && t[p] == '}') { p++; return o; }
    while (true) {
      skip_ws(t, p);
      std::string key = parse_string(t, p);
      skip_ws(t, p);
      if (p >= t.size() || t[p] != ':')
        throw std::runtime_error("JSON: expected ':'");
      p++;
      o.set(key, parse_value(t, p));
      skip_ws(t, p);
      if (p >= t.size()) throw std::runtime_error("JSON: unterminated object");
      if (t[p] == ',') { p++; continue; }
      if (t[p] == '}') { p++; return o; }
      throw std::runtime_error("JSON: bad object separator");
    }
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0.0;
  std::string str_;
  std::vector<Json> arr_;
  std::map<std::string, Json> obj_;
};

// ---------------------------------------------------------------------------
// engine_main: the stdio request loop.
// ---------------------------------------------------------------------------

struct Handlers {
  // DataSource role
  std::function<Json(const Json& params)> read_training;
  // Preparator role
  std::function<Json(const Json& params, const Json& data)> prepare;
  // Algorithm role
  std::function<Json(const Json& params, const Json& data)> train;
  std::function<Json(const Json& model, const Json& query)> predict;
};

inline int engine_main(const Handlers& h) {
  std::ios::sync_with_stdio(false);
  Json model;        // set by "load" or left by "train"
  bool has_model = false;
  std::string line;
  while (std::getline(std::cin, line)) {
    if (line.empty()) continue;
    Json resp = Json::object();
    try {
      Json req = Json::parse(line);
      resp.set("id", req["id"]);
      const std::string& method = req["method"].as_string();
      if (method == "train" && h.train) {
        model = h.train(req["params"], req["data"]);
        has_model = true;
        resp.set("result", model);
      } else if (method == "load") {
        model = req["model"];
        has_model = true;
        resp.set("result", Json(true));
      } else if (method == "predict" && h.predict) {
        if (!has_model) throw std::runtime_error("no model loaded");
        resp.set("result", h.predict(model, req["query"]));
      } else if (method == "read_training" && h.read_training) {
        resp.set("result", h.read_training(req["params"]));
      } else if (method == "prepare" && h.prepare) {
        resp.set("result", h.prepare(req["params"], req["data"]));
      } else {
        throw std::runtime_error("unsupported method: " + method);
      }
    } catch (const std::exception& e) {
      resp.set("error", Json(std::string(e.what())));
    }
    std::cout << resp.dump() << "\n" << std::flush;
  }
  return 0;
}

}  // namespace pio

#endif  // PIO_ENGINE_HPP_
