// Implementation of the PredictionIO-TPU C++ client SDK (see header).

#include "predictionio_client.hpp"

#include <cstring>
#include <sstream>

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

namespace pio {

namespace {

// Tiny percent-encoder for query-string values (access keys are
// url-safe base64 but defensive encoding costs nothing).
std::string url_encode(const std::string& s) {
  static const char* hex = "0123456789ABCDEF";
  std::string out;
  out.reserve(s.size());
  for (unsigned char c : s) {
    if (isalnum(c) || c == '-' || c == '_' || c == '.' || c == '~') {
      out.push_back((char)c);
    } else {
      out.push_back('%');
      out.push_back(hex[c >> 4]);
      out.push_back(hex[c & 15]);
    }
  }
  return out;
}

struct Socket {
  int fd = -1;
  ~Socket() {
    if (fd >= 0) close(fd);
  }
};

}  // namespace

HttpClient::HttpClient(std::string host, int port, double timeout_s)
    : host_(std::move(host)), port_(port), timeout_s_(timeout_s) {}

HttpResponse HttpClient::request(const std::string& method,
                                 const std::string& path,
                                 const std::string& body,
                                 const std::string& content_type) {
  // resolve
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_str = std::to_string(port_);
  int rc = getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) {
    throw ClientError(0, "resolve " + host_ + ": " + gai_strerror(rc));
  }
  Socket sock;
  std::string connect_err;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    sock.fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (sock.fd < 0) continue;
    struct timeval tv;
    tv.tv_sec = (time_t)timeout_s_;
    tv.tv_usec = (suseconds_t)((timeout_s_ - (time_t)timeout_s_) * 1e6);
    setsockopt(sock.fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(sock.fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    int one = 1;
    setsockopt(sock.fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (connect(sock.fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    connect_err = strerror(errno);
    close(sock.fd);
    sock.fd = -1;
  }
  freeaddrinfo(res);
  if (sock.fd < 0) {
    throw ClientError(0, "connect " + host_ + ":" + port_str + " failed: " +
                             connect_err);
  }

  // send request (Connection: close keeps framing trivial)
  std::ostringstream req;
  req << method << " " << path << " HTTP/1.1\r\n"
      << "Host: " << host_ << ":" << port_str << "\r\n"
      << "Connection: close\r\n";
  if (!body.empty() || method == "POST" || method == "PUT") {
    req << "Content-Type: " << content_type << "\r\n"
        << "Content-Length: " << body.size() << "\r\n";
  }
  req << "\r\n" << body;
  const std::string data = req.str();
  size_t sent = 0;
  while (sent < data.size()) {
    ssize_t n = send(sock.fd, data.data() + sent, data.size() - sent, 0);
    if (n <= 0) throw ClientError(0, "send failed: " + std::string(strerror(errno)));
    sent += (size_t)n;
  }

  // read full response
  std::string raw;
  char buf[8192];
  for (;;) {
    ssize_t n = recv(sock.fd, buf, sizeof(buf), 0);
    if (n < 0) throw ClientError(0, "recv failed: " + std::string(strerror(errno)));
    if (n == 0) break;
    raw.append(buf, (size_t)n);
  }

  // parse status line + headers
  size_t hdr_end = raw.find("\r\n\r\n");
  if (hdr_end == std::string::npos || raw.compare(0, 5, "HTTP/") != 0) {
    throw ClientError(0, "malformed HTTP response");
  }
  HttpResponse out;
  {
    size_t sp1 = raw.find(' ');
    out.status = atoi(raw.c_str() + sp1 + 1);
  }
  std::string headers = raw.substr(0, hdr_end);
  std::string payload = raw.substr(hdr_end + 4);
  // chunked decoding (servers speak HTTP/1.1; with Connection: close most
  // respond with Content-Length, but decode chunked when present)
  bool chunked = false;
  {
    std::string lower;
    lower.reserve(headers.size());
    for (char c : headers) lower.push_back((char)tolower((unsigned char)c));
    chunked = lower.find("transfer-encoding: chunked") != std::string::npos;
  }
  if (chunked) {
    std::string decoded;
    size_t pos = 0;
    while (pos < payload.size()) {
      size_t eol = payload.find("\r\n", pos);
      if (eol == std::string::npos) break;
      long len = strtol(payload.c_str() + pos, nullptr, 16);
      if (len <= 0) break;
      decoded.append(payload, eol + 2, (size_t)len);
      pos = eol + 2 + (size_t)len + 2;
    }
    out.body = decoded;
  } else {
    out.body = payload;
  }
  return out;
}

// ---------------------------------------------------------------------------

EventClient::EventClient(std::string host, int port, std::string access_key)
    : http_(std::move(host), port), access_key_(std::move(access_key)) {}

std::string EventClient::create_event(const std::string& event_json) {
  auto resp = http_.request(
      "POST", "/events.json?accessKey=" + url_encode(access_key_), event_json);
  if (resp.status != 201) {
    throw ClientError(resp.status, "create_event: " + resp.body);
  }
  // response: {"eventId": "..."} — extract without a JSON dependency
  size_t key = resp.body.find("\"eventId\"");
  if (key == std::string::npos) return resp.body;
  size_t q1 = resp.body.find('"', resp.body.find(':', key));
  size_t q2 = resp.body.find('"', q1 + 1);
  return resp.body.substr(q1 + 1, q2 - q1 - 1);
}

std::string EventClient::create_events_batch(
    const std::string& events_json_array) {
  auto resp = http_.request(
      "POST", "/batches/events.json?accessKey=" + url_encode(access_key_),
      events_json_array);
  if (resp.status != 200) {
    throw ClientError(resp.status, "create_events_batch: " + resp.body);
  }
  return resp.body;
}

std::string EventClient::get_event(const std::string& event_id) {
  auto resp = http_.request(
      "GET",
      "/events/" + url_encode(event_id) +
          ".json?accessKey=" + url_encode(access_key_),
      "");
  if (resp.status != 200) {
    throw ClientError(resp.status, "get_event: " + resp.body);
  }
  return resp.body;
}

bool EventClient::delete_event(const std::string& event_id) {
  auto resp = http_.request(
      "DELETE",
      "/events/" + url_encode(event_id) +
          ".json?accessKey=" + url_encode(access_key_),
      "");
  // wire parity: 200 {"message": "Found"} when deleted, 404 when absent
  if (resp.status == 404) return false;
  if (resp.status != 200) {
    throw ClientError(resp.status, "delete_event: " + resp.body);
  }
  return true;
}

std::string EventClient::find_events(const std::string& extra_query) {
  auto resp = http_.request(
      "GET", "/events.json?accessKey=" + url_encode(access_key_) + extra_query,
      "");
  if (resp.status != 200) {
    throw ClientError(resp.status, "find_events: " + resp.body);
  }
  return resp.body;
}

std::string EventClient::stats() {
  auto resp = http_.request(
      "GET", "/stats.json?accessKey=" + url_encode(access_key_), "");
  if (resp.status != 200) {
    throw ClientError(resp.status, "stats: " + resp.body);
  }
  return resp.body;
}

// ---------------------------------------------------------------------------

EngineClient::EngineClient(std::string host, int port)
    : http_(std::move(host), port) {}

std::string EngineClient::send_query(const std::string& query_json) {
  auto resp = http_.request("POST", "/queries.json", query_json);
  if (resp.status != 200) {
    throw ClientError(resp.status, "send_query: " + resp.body);
  }
  return resp.body;
}

std::string EngineClient::status() {
  auto resp = http_.request("GET", "/", "");
  if (resp.status != 200) {
    throw ClientError(resp.status, "status: " + resp.body);
  }
  return resp.body;
}

}  // namespace pio
