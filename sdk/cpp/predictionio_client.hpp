// PredictionIO-TPU C++ client SDK.
//
// Second-language client surface (the rebuild's analogue of the reference's
// Java controller shim + client SDKs, core/src/main/java/io/prediction/
// controller/java/): a dependency-free HTTP client for the two REST
// surfaces every deployment exposes —
//
//   EventClient  -> the Event Server   (POST/GET/DELETE /events.json,
//                                       GET /stats.json; EventAPI.scala
//                                       routes, default port 7070)
//   EngineClient -> the Query Server   (POST /queries.json;
//                                       CreateServer.scala:458, port 8000)
//
// JSON crosses the boundary as strings: callers bring their own JSON
// library (the reference Java SDK does the same with Gson at the edge).
// Plain POSIX sockets + HTTP/1.1, no external dependencies.
//
// Usage:
//   pio::EventClient events("127.0.0.1", 7070, access_key);
//   std::string id = events.create_event(R"({"event":"rate",...})");
//   pio::EngineClient engine("127.0.0.1", 8000);
//   std::string result = engine.send_query(R"({"user":"u1","num":10})");

#ifndef PREDICTIONIO_CLIENT_HPP_
#define PREDICTIONIO_CLIENT_HPP_

#include <stdexcept>
#include <string>

namespace pio {

// Thrown on transport failures and non-2xx responses.
class ClientError : public std::runtime_error {
 public:
  ClientError(int status, const std::string& message)
      : std::runtime_error(message), status_(status) {}
  // HTTP status, or 0 for transport-level failures.
  int status() const { return status_; }

 private:
  int status_;
};

struct HttpResponse {
  int status = 0;
  std::string body;
};

// Minimal HTTP/1.1 client: one connection per request (keep-alive is the
// servers' default but reconnect-per-call keeps the SDK stateless and
// thread-compatible — callers wanting throughput pool EventClient
// instances per thread).
class HttpClient {
 public:
  HttpClient(std::string host, int port, double timeout_s = 30.0);

  HttpResponse request(const std::string& method, const std::string& path,
                       const std::string& body,
                       const std::string& content_type = "application/json");

 private:
  std::string host_;
  int port_;
  double timeout_s_;
};

// Client for the Event Server REST API (EventAPI.scala:168-345 surface).
class EventClient {
 public:
  EventClient(std::string host, int port, std::string access_key);

  // POST /events.json — returns the created event id.
  // `event_json` is the wire-format event dict.
  std::string create_event(const std::string& event_json);

  // POST /batches/events.json — bulk ingestion. `events_json_array` is a
  // JSON array of wire-format event dicts; returns the server's
  // per-event result array (status 201 + eventId, or 400 + message) as
  // raw JSON.
  std::string create_events_batch(const std::string& events_json_array);

  // GET /events/<id>.json — returns the event JSON.
  std::string get_event(const std::string& event_id);

  // DELETE /events/<id>.json — true when the event existed.
  bool delete_event(const std::string& event_id);

  // GET /events.json with optional query filters appended verbatim,
  // e.g. "&event=rate&limit=20". Returns the JSON array.
  std::string find_events(const std::string& extra_query = "");

  // GET /stats.json (requires the server's --stats mode).
  std::string stats();

 private:
  HttpClient http_;
  std::string access_key_;
};

// Client for a deployed engine's query API (CreateServer.scala:458).
class EngineClient {
 public:
  EngineClient(std::string host, int port);

  // POST /queries.json — returns the PredictedResult JSON.
  std::string send_query(const std::string& query_json);

  // GET / — the status page (HTML).
  std::string status();

 private:
  HttpClient http_;
};

}  // namespace pio

#endif  // PREDICTIONIO_CLIENT_HPP_
